"""StateStore — MVCC snapshot store with index watermarks.

Reference: nomad/state/state_store.go (6,446 LoC on go-memdb) and
nomad/fsm.go (Raft log application). The semantics that matter and are
kept here:

- **Snapshot isolation.** Schedulers run against an immutable snapshot
  while writers proceed (memdb MVCC). Implemented as copy-on-first-write-
  after-snapshot: ``snapshot()`` freezes the current table dicts; the next
  write to a frozen table copies it. Secondary-index values are immutable
  ``frozenset``s so snapshots share them safely.
- **Index watermarks.** Every write carries a monotonically increasing
  index (the Raft log index analog). ``wait_for_index`` is the worker's
  ``snapshotMinIndex`` barrier (nomad/worker.go:536-549): don't schedule
  an eval against state older than the index that created it.
- **UpsertPlanResults** applies a committed plan atomically: stops,
  placements, preemptions, eval updates (state_store.go UpsertPlanResults).
- **Blocking queries.** A condition variable broadcast on every index bump
  backs blocking/watch reads (memdb WatchSet analog).
"""

from __future__ import annotations

import threading
import time as _time
from typing import Callable, Iterable, Optional

from ..structs import (
    ALLOC_CLIENT_LOST,
    ALLOC_DESIRED_STOP,
    Allocation,
    Evaluation,
    Job,
    Node,
    PlanResult,
)

JOB_TRACKED_VERSIONS = 6  # structsJobTrackedVersions


class SchedulerConfiguration:
    """Runtime scheduler config stored in state (the Raft-resident knob the
    TPU algorithm registers under). Reference: structs.SchedulerConfiguration
    (nomad/structs/operator.go:128-220, default binpack :164-169)."""

    # class-level defaults double as the fallback for configs restored
    # from older snapshots (pickle skips __init__)
    placement_explanations = True
    throughput_source = "declared"

    def __init__(
        self,
        scheduler_algorithm: str = "binpack",
        preemption_system_enabled: bool = True,
        preemption_batch_enabled: bool = False,
        preemption_service_enabled: bool = False,
        memory_oversubscription_enabled: bool = False,
        pause_eval_broker: bool = False,
        placement_explanations: bool = True,
        throughput_source: str = "declared",
    ):
        self.scheduler_algorithm = scheduler_algorithm
        self.preemption_system_enabled = preemption_system_enabled
        self.preemption_batch_enabled = preemption_batch_enabled
        self.preemption_service_enabled = preemption_service_enabled
        self.memory_oversubscription_enabled = memory_oversubscription_enabled
        self.pause_eval_broker = pause_eval_broker
        # score provenance (obs/explain.py): when off, placements are
        # bit-identical (the gate is Python-level) but no explanations
        # are built, recorded, or served
        self.placement_explanations = placement_explanations
        # hetero throughput matrix source (obs/calibrate.py): "declared"
        # = jobspec coefficients (byte-identical pre-calibration path),
        # "learned" = the ThroughputEstimator's online telemetry values
        self.throughput_source = throughput_source


class _Tables:
    """The raw table/index dict bundle shared between store and snapshots."""

    __slots__ = (
        "nodes",
        "jobs",
        "job_versions",
        "evals",
        "allocs",
        "allocs_by_node",
        "allocs_by_job",
        "evals_by_job",
        "deployments",
        "deployments_by_job",
        "acl_policies",
        "acl_tokens",
        "acl_tokens_by_secret",
        "csi_volumes",
        "namespaces",
        "scaling_events",
        "indexes",
        "scheduler_config",
    )

    def __init__(self):
        self.nodes: dict[str, Node] = {}
        self.jobs: dict[tuple[str, str], Job] = {}
        self.job_versions: dict[tuple[str, str], tuple] = {}
        self.evals: dict[str, Evaluation] = {}
        self.allocs: dict[str, Allocation] = {}
        self.allocs_by_node: dict[str, frozenset[str]] = {}
        self.allocs_by_job: dict[tuple[str, str], frozenset[str]] = {}
        self.evals_by_job: dict[tuple[str, str], frozenset[str]] = {}
        self.deployments: dict[str, object] = {}
        self.deployments_by_job: dict[tuple[str, str], frozenset[str]] = {}
        self.acl_policies: dict[str, object] = {}
        self.acl_tokens: dict[str, object] = {}  # accessor_id → ACLToken
        self.acl_tokens_by_secret: dict[str, str] = {}  # secret → accessor
        self.csi_volumes: dict[str, object] = {}  # volume id → CSIVolume
        self.namespaces: dict[str, object] = {}  # name → Namespace
        # (ns, job_id) → tuple of scaling event dicts, newest first
        self.scaling_events: dict[tuple[str, str], tuple] = {}
        self.indexes: dict[str, int] = {}
        self.scheduler_config: SchedulerConfiguration = SchedulerConfiguration()

    TABLE_NAMES = (
        "nodes",
        "jobs",
        "job_versions",
        "evals",
        "allocs",
        "allocs_by_node",
        "allocs_by_job",
        "evals_by_job",
        "deployments",
        "deployments_by_job",
        "acl_policies",
        "acl_tokens",
        "acl_tokens_by_secret",
        "csi_volumes",
        "namespaces",
        "scaling_events",
        "indexes",
    )


class ChangeJournal:
    """Bounded append-only log of (index, table, key) write records — the
    watch-set analog (nomad/state/state_store.go WatchSet) that lets the
    device-state cache refresh resident tensors incrementally instead of
    re-flattening the cluster per eval (SURVEY.md §7 'latency floor').

    Only the tables the flattening layer consumes are journaled (nodes,
    allocs). Readers ask for changes in an index interval; ``None`` means
    the journal was trimmed past the interval and the reader must rebuild.
    """

    def __init__(self, cap: int = 500_000):
        self._entries: list[tuple[int, str, object]] = []
        self._cap = cap
        self._floor = 0  # records with index <= floor may have been trimmed
        self._lock = threading.Lock()

    def note(self, index: int, table: str, key) -> None:
        with self._lock:
            self._entries.append((index, table, key))
            if len(self._entries) > self._cap:
                drop = len(self._entries) // 2
                self._floor = self._entries[drop - 1][0]
                del self._entries[:drop]

    def since(self, after_index: int, upto_index: int):
        """Changes with after_index < index <= upto_index, as
        {table: set(keys)}, or None if the interval fell off the journal."""
        with self._lock:
            if after_index < self._floor:
                return None
            out: dict[str, set] = {}
            # entries are appended in index order; scan from the back
            for idx, table, key in reversed(self._entries):
                if idx <= after_index:
                    break
                if idx <= upto_index:
                    out.setdefault(table, set()).add(key)
            return out


class StateSnapshot:
    """An immutable point-in-time view. All read methods of StateStore are
    defined on this class; the store itself reads through a live view."""

    def __init__(self, tables: _Tables, index: int, journal=None):
        self._t = tables
        self.index = index
        self.journal = journal

    # -- namespaces --------------------------------------------------------
    def namespace_by_name(self, name: str):
        return self._t.namespaces.get(name)

    def namespaces(self) -> list:
        return list(self._t.namespaces.values())

    def scaling_events(self, namespace: str, job_id: str) -> list:
        return list(self._t.scaling_events.get((namespace, job_id), ()))

    # -- nodes ------------------------------------------------------------
    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._t.nodes.get(node_id)

    def nodes(self) -> Iterable[Node]:
        return self._t.nodes.values()

    def ready_nodes_in_dcs(self, datacenters: Iterable[str]) -> list[Node]:
        """readyNodesInDCs (scheduler/util.go:279)."""
        dcs = set(datacenters)
        return [n for n in self._t.nodes.values() if n.ready() and n.datacenter in dcs]

    # -- jobs -------------------------------------------------------------
    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        return self._t.jobs.get((namespace, job_id))

    def jobs(self) -> Iterable[Job]:
        return self._t.jobs.values()

    def job_version(self, namespace: str, job_id: str, version: int) -> Optional[Job]:
        for j in self._t.job_versions.get((namespace, job_id), ()):
            if j.version == version:
                return j
        return None

    def job_versions_list(self, namespace: str, job_id: str) -> list[Job]:
        return list(self._t.job_versions.get((namespace, job_id), ()))

    # -- evals ------------------------------------------------------------
    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._t.evals.get(eval_id)

    def evals(self) -> Iterable[Evaluation]:
        return self._t.evals.values()

    def evals_by_job(self, namespace: str, job_id: str) -> list[Evaluation]:
        ids = self._t.evals_by_job.get((namespace, job_id), frozenset())
        return [self._t.evals[i] for i in ids if i in self._t.evals]

    # -- allocs -----------------------------------------------------------
    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self._t.allocs.get(alloc_id)

    def allocs(self) -> Iterable[Allocation]:
        return self._t.allocs.values()

    def allocs_by_node(self, node_id: str) -> list[Allocation]:
        ids = self._t.allocs_by_node.get(node_id, frozenset())
        return [self._t.allocs[i] for i in ids if i in self._t.allocs]

    def allocs_by_node_terminal(self, node_id: str, terminal: bool) -> list[Allocation]:
        return [
            a for a in self.allocs_by_node(node_id) if a.terminal_status() == terminal
        ]

    def allocs_by_job(self, namespace: str, job_id: str) -> list[Allocation]:
        ids = self._t.allocs_by_job.get((namespace, job_id), frozenset())
        return [self._t.allocs[i] for i in ids if i in self._t.allocs]

    def allocs_by_eval(self, eval_id: str) -> list[Allocation]:
        return [a for a in self._t.allocs.values() if a.eval_id == eval_id]

    # -- deployments ------------------------------------------------------
    def deployment_by_id(self, deployment_id: str):
        return self._t.deployments.get(deployment_id)

    def deployments(self):
        return self._t.deployments.values()

    def latest_deployment_by_job(self, namespace: str, job_id: str):
        ids = self._t.deployments_by_job.get((namespace, job_id), frozenset())
        best = None
        for i in ids:
            d = self._t.deployments.get(i)
            if d is not None and (best is None or d.create_index > best.create_index):
                best = d
        return best

    # -- ACL ---------------------------------------------------------------
    def acl_policy_by_name(self, name: str):
        return self._t.acl_policies.get(name)

    def acl_policies(self) -> Iterable:
        return self._t.acl_policies.values()

    def acl_token_by_accessor(self, accessor_id: str):
        return self._t.acl_tokens.get(accessor_id)

    def acl_token_by_secret(self, secret_id: str):
        accessor = self._t.acl_tokens_by_secret.get(secret_id)
        return self._t.acl_tokens.get(accessor) if accessor else None

    def acl_tokens(self) -> Iterable:
        return self._t.acl_tokens.values()

    def acl_bootstrapped(self) -> bool:
        return self._t.indexes.get("acl_bootstrap", 0) > 0

    # -- CSI volumes -------------------------------------------------------
    def csi_volume_by_id(self, volume_id: str):
        return self._t.csi_volumes.get(volume_id)

    def csi_volumes(self) -> Iterable:
        return self._t.csi_volumes.values()

    def csi_plugins(self) -> dict:
        """Derived CSI plugin aggregate health: plugin id → CSIPlugin,
        counting healthy node-plugin instances across the node table
        (structs.CSIPlugin is derived state in the reference too)."""
        from ..structs.volumes import CSIPlugin

        out: dict[str, CSIPlugin] = {}
        for node in self._t.nodes.values():
            for pid, info in node.csi_node_plugins.items():
                p = out.setdefault(pid, CSIPlugin(id=pid))
                if info.healthy:
                    p.nodes_healthy += 1
        return out

    # -- meta -------------------------------------------------------------
    def scheduler_config(self) -> SchedulerConfiguration:
        return self._t.scheduler_config

    def table_index(self, table: str) -> int:
        return self._t.indexes.get(table, 0)


class StateStore(StateSnapshot):
    """The live, writable store. Reads see the latest committed state."""

    def __init__(self):
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._frozen: set[str] = set()
        self._latest_index = 0
        self._listeners: list[Callable[[str, int], None]] = []
        super().__init__(_Tables(), 0, journal=ChangeJournal())

    # -- snapshot machinery ----------------------------------------------
    @property
    def latest_index(self) -> int:
        return self._latest_index

    def snapshot(self) -> StateSnapshot:
        """Freeze current tables; writers copy-on-first-write after this."""
        from ..chaos.plane import chaos_site

        # a raise here models a failed state read at the top of a
        # scheduling pass; the worker nacks its batch for redelivery
        chaos_site("store.snapshot")
        with self._lock:
            self._frozen = set(_Tables.TABLE_NAMES)
            return StateSnapshot(
                self._shallow_tables(), self._latest_index, journal=self.journal
            )

    def _shallow_tables(self) -> _Tables:
        t = _Tables.__new__(_Tables)
        for name in _Tables.TABLE_NAMES:
            setattr(t, name, getattr(self._t, name))
        t.scheduler_config = self._t.scheduler_config
        return t

    def _own(self, table: str) -> dict:
        d = getattr(self._t, table)
        if table in self._frozen:
            d = dict(d)
            setattr(self._t, table, d)
            self._frozen.discard(table)
        return d

    def _bump(self, index: int, *tables: str) -> None:
        self._latest_index = max(self._latest_index, index)
        idx = self._own("indexes")
        for tb in tables:
            idx[tb] = index
        self._cond.notify_all()
        for fn in self._listeners:
            for tb in tables:
                fn(tb, index)

    def bump_index(self, index: int) -> None:
        """Advance latest_index without touching tables — raft NOOP/barrier
        entries consume log indexes that must stay visible to blocking
        queries (SnapshotMinIndex semantics, worker.go:536)."""
        with self._lock:
            self._latest_index = max(self._latest_index, index)
            self._cond.notify_all()

    def add_listener(self, fn: Callable[[str, int], None]) -> None:
        """Table-change listener (the event-broker / blocked-evals hook)."""
        with self._lock:
            self._listeners.append(fn)

    def wait_for_index(self, index: int, timeout: float = 5.0) -> bool:
        """snapshotMinIndex barrier (worker.go:536-549)."""
        deadline = _time.monotonic() + timeout
        with self._lock:
            while self._latest_index < index:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    # -- index maintenance helpers ---------------------------------------
    @staticmethod
    def _idx_add(d: dict, key, value: str) -> None:
        d[key] = d.get(key, frozenset()) | {value}

    @staticmethod
    def _idx_del(d: dict, key, value: str) -> None:
        cur = d.get(key)
        if cur is None:
            return
        nxt = cur - {value}
        if nxt:
            d[key] = nxt
        else:
            d.pop(key, None)

    # -- nodes ------------------------------------------------------------
    def upsert_node(self, index: int, node: Node) -> None:
        with self._lock:
            nodes = self._own("nodes")
            existing = nodes.get(node.id)
            if existing is not None:
                node.create_index = existing.create_index
            else:
                node.create_index = index
            node.modify_index = index
            if not node.computed_class:
                node.compute_class()
            nodes[node.id] = node
            self.journal.note(index, "nodes", node.id)
            self._bump(index, "nodes")

    def delete_node(self, index: int, node_id: str) -> None:
        with self._lock:
            self._own("nodes").pop(node_id, None)
            self.journal.note(index, "nodes", node_id)
            self._bump(index, "nodes")

    def update_node_status(self, index: int, node_id: str, status: str) -> None:
        with self._lock:
            nodes = self._own("nodes")
            n = nodes.get(node_id)
            if n is None:
                raise KeyError(f"node {node_id} not found")
            import copy

            n2 = copy.copy(n)
            n2.status = status
            n2.modify_index = index
            nodes[node_id] = n2
            self.journal.note(index, "nodes", node_id)
            self._bump(index, "nodes")

    def update_node_eligibility(self, index: int, node_id: str, elig: str) -> None:
        with self._lock:
            nodes = self._own("nodes")
            n = nodes.get(node_id)
            if n is None:
                raise KeyError(f"node {node_id} not found")
            import copy

            n2 = copy.copy(n)
            n2.scheduling_eligibility = elig
            n2.modify_index = index
            nodes[node_id] = n2
            self.journal.note(index, "nodes", node_id)
            self._bump(index, "nodes")

    def update_node_drain(
        self, index: int, node_id: str, drain, eligibility: str = ""
    ) -> None:
        """Set/clear the drain strategy. ``eligibility`` overrides the
        default (draining ⇒ ineligible, cleared ⇒ eligible) — the drainer
        clears the strategy but keeps the node ineligible."""
        from ..structs import NODE_SCHED_INELIGIBLE, NODE_SCHED_ELIGIBLE

        with self._lock:
            nodes = self._own("nodes")
            n = nodes.get(node_id)
            if n is None:
                raise KeyError(f"node {node_id} not found")
            import copy

            n2 = copy.copy(n)
            n2.drain = drain
            n2.scheduling_eligibility = eligibility or (
                NODE_SCHED_INELIGIBLE if drain is not None else NODE_SCHED_ELIGIBLE
            )
            n2.modify_index = index
            nodes[node_id] = n2
            self.journal.note(index, "nodes", node_id)
            self._bump(index, "nodes")

    # -- jobs -------------------------------------------------------------
    def upsert_job(self, index: int, job: Job) -> None:
        """UpsertJob: bump version on change, retain bounded version history."""
        with self._lock:
            jobs = self._own("jobs")
            key = job.namespaced_id()
            existing = jobs.get(key)
            if existing is not None:
                job.create_index = existing.create_index
                job.version = existing.version + 1
            else:
                job.create_index = index
                job.version = 0
            job.modify_index = index
            job.job_modify_index = index
            if job.status not in ("dead",):
                job.status = "pending" if existing is None else job.status
            jobs[key] = job
            versions = self._own("job_versions")
            hist = (job,) + versions.get(key, ())
            versions[key] = hist[:JOB_TRACKED_VERSIONS]
            self._bump(index, "jobs", "job_versions")

    def delete_job(self, index: int, namespace: str, job_id: str) -> None:
        with self._lock:
            self._own("jobs").pop((namespace, job_id), None)
            self._own("job_versions").pop((namespace, job_id), None)
            self._bump(index, "jobs", "job_versions")

    def mark_job_stable(self, index: int, job: Job) -> None:
        """Record a job version as a known-good rollback target
        (UpdateJobStability in the reference)."""
        with self._lock:
            jobs = self._own("jobs")
            key = job.namespaced_id()
            if jobs.get(key) is not None and jobs[key].version == job.version:
                jobs[key] = job
            versions = self._own("job_versions")
            hist = tuple(
                job if j.version == job.version else j
                for j in versions.get(key, ())
            )
            versions[key] = hist
            self._bump(index, "jobs", "job_versions")

    def update_job_status(self, index: int, namespace: str, job_id: str, status: str):
        with self._lock:
            jobs = self._own("jobs")
            j = jobs.get((namespace, job_id))
            if j is None:
                return
            import copy

            j2 = copy.copy(j)
            j2.status = status
            j2.modify_index = index
            jobs[(namespace, job_id)] = j2
            self._bump(index, "jobs")

    # -- evals ------------------------------------------------------------
    def upsert_evals(self, index: int, evals: Iterable[Evaluation]) -> None:
        with self._lock:
            table = self._own("evals")
            by_job = self._own("evals_by_job")
            for ev in evals:
                existing = table.get(ev.id)
                ev.create_index = existing.create_index if existing else index
                ev.modify_index = index
                table[ev.id] = ev
                self._idx_add(by_job, (ev.namespace, ev.job_id), ev.id)
            self._bump(index, "evals")

    def delete_evals(self, index: int, eval_ids: Iterable[str]) -> None:
        with self._lock:
            table = self._own("evals")
            by_job = self._own("evals_by_job")
            for eid in eval_ids:
                ev = table.pop(eid, None)
                if ev is not None:
                    self._idx_del(by_job, (ev.namespace, ev.job_id), eid)
            self._bump(index, "evals")

    # -- allocs -----------------------------------------------------------
    def upsert_allocs(self, index: int, allocs: Iterable[Allocation]) -> None:
        with self._lock:
            self._upsert_allocs_locked(index, allocs)
            self._bump(index, "allocs")

    def _upsert_allocs_locked(self, index: int, allocs: Iterable[Allocation]) -> None:
        import copy as _copy

        table = self._own("allocs")
        by_node = self._own("allocs_by_node")
        by_job = self._own("allocs_by_job")
        for a in allocs:
            # Denormalize: plans ship with alloc.job stripped
            # (Plan.normalize); re-attach the stored job at the alloc's
            # version so version diffing / device asks keep working —
            # mirrors StateStore.DenormalizeAllocationsMap.
            if a.job is None:
                j = self._t.jobs.get((a.namespace, a.job_id))
                if j is not None and j.version != a.job_version:
                    for old in self._t.job_versions.get((a.namespace, a.job_id), ()):
                        if old.version == a.job_version:
                            j = old
                            break
                a.job = j
            # Maintain the replacement chain: the previous alloc learns its
            # successor (state_store.go UpsertAllocs sets NextAllocation).
            if a.previous_allocation:
                prev = table.get(a.previous_allocation)
                if prev is not None and prev.next_allocation != a.id:
                    prev2 = _copy.copy(prev)
                    prev2.next_allocation = a.id
                    prev2.modify_index = index
                    table[prev.id] = prev2
            existing = table.get(a.id)
            if existing is not None:
                a.create_index = existing.create_index
                # Preserve client-reported fields on server-side updates
                # (state_store.go UpsertAllocs keeps ClientStatus unless
                # the update sets it).
                if a.client_status == "" and existing.client_status:
                    a.client_status = existing.client_status
                if existing.node_id and existing.node_id != a.node_id:
                    self._idx_del(by_node, existing.node_id, a.id)
                    self.journal.note(index, "node_allocs", existing.node_id)
            else:
                a.create_index = index
            a.modify_index = index
            table[a.id] = a
            if a.node_id:
                self._idx_add(by_node, a.node_id, a.id)
                self.journal.note(index, "node_allocs", a.node_id)
            self._idx_add(by_job, (a.namespace, a.job_id), a.id)

    def delete_allocs(self, index: int, alloc_ids: Iterable[str]) -> None:
        with self._lock:
            table = self._own("allocs")
            by_node = self._own("allocs_by_node")
            by_job = self._own("allocs_by_job")
            for aid in alloc_ids:
                a = table.pop(aid, None)
                if a is not None:
                    if a.node_id:
                        self._idx_del(by_node, a.node_id, aid)
                        self.journal.note(index, "node_allocs", a.node_id)
                    self._idx_del(by_job, (a.namespace, a.job_id), aid)
            self._bump(index, "allocs")

    def delete_deployment(self, index: int, deployment_id: str) -> None:
        with self._lock:
            table = self._own("deployments")
            d = table.pop(deployment_id, None)
            if d is not None:
                self._idx_del(
                    self._own("deployments_by_job"),
                    (d.namespace, d.job_id),
                    deployment_id,
                )
            self._bump(index, "deployments")

    def update_allocs_from_client(self, index: int, updates: Iterable[Allocation]):
        """Client status sync (Node.UpdateAlloc): merge client-owned fields
        onto the server copy."""
        import copy

        with self._lock:
            table = self._own("allocs")
            for upd in updates:
                existing = table.get(upd.id)
                if existing is None:
                    continue
                a = copy.copy(existing)
                a.client_status = upd.client_status
                a.client_description = upd.client_description
                a.task_states = upd.task_states or a.task_states
                # client-side health verdict (allochealth tracker): the
                # deployment watcher consumes it for canary gating; the
                # first verdict wins (tracker.go never flips a verdict)
                if upd.deployment_status is not None and (
                    existing.deployment_status is None
                    or existing.deployment_status.healthy is None
                ):
                    a.deployment_status = upd.deployment_status
                a.modify_index = index
                table[a.id] = a
                if a.node_id:
                    self.journal.note(index, "node_allocs", a.node_id)
            self._bump(index, "allocs")

    # -- deployments -------------------------------------------------------
    def upsert_deployment(self, index: int, deployment) -> None:
        with self._lock:
            table = self._own("deployments")
            existing = table.get(deployment.id)
            if (
                existing is not None
                and not existing.active()
                and deployment.active()
            ):
                # same-id upsert flipping a TERMINAL deployment back to
                # active can only be a racing pause/resume (new rollouts
                # mint new ids) — refuse the resurrection
                return
            deployment.create_index = existing.create_index if existing else index
            deployment.modify_index = index
            table[deployment.id] = deployment
            self._idx_add(
                self._own("deployments_by_job"),
                (deployment.namespace, deployment.job_id),
                deployment.id,
            )
            self._bump(index, "deployments")

    # -- plan results (the FSM's ApplyPlanResults) -------------------------
    def upsert_plan_results(self, index: int, result: PlanResult, eval_id: str = ""):
        """Apply a committed plan atomically: stops/evictions, preempted
        allocs, then placements (state_store.go UpsertPlanResults)."""
        with self._lock:
            self._apply_plan_result_locked(index, result)
            self._bump(index, "allocs", "deployments")

    def upsert_merged_plan_results(
        self, index: int, results: list[PlanResult]
    ) -> None:
        """Apply a whole batched pass's committed member results as ONE
        store transaction: every member's stops/preemptions/placements
        land under a single lock acquisition and a single index bump, so
        a batch of B plans costs one listener fan-out instead of B."""
        with self._lock:
            for result in results:
                self._apply_plan_result_locked(index, result)
            self._bump(index, "allocs", "deployments")

    def _apply_plan_result_locked(self, index: int, result: PlanResult) -> None:
        updates: list[Allocation] = []
        for allocs in result.node_update.values():
            updates.extend(allocs)
        for allocs in result.node_preemptions.values():
            updates.extend(allocs)
        for allocs in result.node_allocation.values():
            updates.extend(allocs)
        self._upsert_allocs_locked(index, updates)
        for allocs in result.node_allocation.values():
            for a in allocs:
                self._csi_claim_for_alloc_locked(index, a)
        for du in result.deployment_updates:
            self._update_deployment_status_locked(
                index,
                du["deployment_id"],
                du["status"],
                du.get("description", ""),
            )
        if result.deployment is not None:
            table = self._own("deployments")
            d = result.deployment
            existing = table.get(d.id)
            d.create_index = existing.create_index if existing else index
            d.modify_index = index
            table[d.id] = d
            self._idx_add(
                self._own("deployments_by_job"),
                (d.namespace, d.job_id),
                d.id,
            )

    # -- CSI volume writers ------------------------------------------------
    def upsert_csi_volume(self, index: int, vol) -> None:
        with self._lock:
            table = self._own("csi_volumes")
            existing = table.get(vol.id)
            if existing is not None:
                # the reference refuses spec changes on an in-use volume
                # (csi_endpoint.go Register → vol.Validate + claim check)
                if existing.in_use():
                    for f in ("namespace", "plugin_id", "access_mode",
                              "attachment_mode"):
                        if getattr(vol, f) != getattr(existing, f):
                            raise ValueError(
                                f"volume {vol.id} is in use; cannot change "
                                f"{f} from {getattr(existing, f)!r} to "
                                f"{getattr(vol, f)!r}"
                            )
                # re-registration must not wipe live claim state
                vol.read_claims = dict(existing.read_claims)
                vol.write_claims = dict(existing.write_claims)
                vol.past_claims = dict(existing.past_claims)
                vol.external_claims = set(existing.external_claims)
                vol.create_index = existing.create_index
            else:
                vol.create_index = index
            vol.modify_index = index
            table[vol.id] = vol
            self._bump(index, "csi_volumes")

    def restore_csi_volume(self, vol) -> None:
        """Snapshot restore: insert verbatim, preserving indexes."""
        with self._lock:
            self._own("csi_volumes")[vol.id] = vol
            self._latest_index = max(self._latest_index, vol.modify_index)

    def deregister_csi_volume(
        self, index: int, volume_id: str, force: bool = False
    ) -> None:
        with self._lock:
            table = self._own("csi_volumes")
            vol = table.get(volume_id)
            if vol is None:
                raise KeyError(f"volume not found: {volume_id}")
            if vol.in_use() and not force:
                raise ValueError(f"volume in use: {volume_id}")
            del table[volume_id]
            self._bump(index, "csi_volumes")

    def csi_claim(
        self,
        index: int,
        volume_id: str,
        alloc_id: str,
        node_id: str,
        read_only: bool,
        external: bool = False,
    ) -> bool:
        with self._lock:
            return self._csi_claim_locked(
                index, volume_id, alloc_id, node_id, read_only,
                external=external,
            )

    def _csi_claim_locked(
        self, index, volume_id, alloc_id, node_id, read_only, external=False
    ) -> bool:
        import copy as _copy

        table = self._own("csi_volumes")
        vol = table.get(volume_id)
        if vol is None:
            return False
        vol = _copy.deepcopy(vol)  # snapshots keep the old claim state
        if not vol.claim(alloc_id, node_id, read_only):
            return False
        if external:
            vol.external_claims.add(alloc_id)
        vol.modify_index = index
        table[volume_id] = vol
        self._bump(index, "csi_volumes")
        return True

    def _csi_claim_for_alloc_locked(self, index: int, alloc) -> None:
        """Claim the CSI volumes a freshly-placed alloc's group requests
        (the reference claims via the client Claim RPC at alloc start;
        claiming at plan commit keeps claim counts correct for the very
        next scheduling pass)."""
        if alloc.client_status != "pending" or alloc.job is None:
            return
        tg = alloc.job.lookup_task_group(alloc.task_group)
        if tg is None or not tg.volumes:
            return
        for req in tg.volumes.values():
            if req.type != "csi":
                continue
            vid = req.source
            if req.per_alloc:
                per = f"{req.source}[{alloc.index()}]"
                if per in self._t.csi_volumes:
                    vid = per
            if not self._csi_claim_locked(
                index, vid, alloc.id, alloc.node_id, req.read_only
            ):
                # plan-apply verification should make this unreachable;
                # an external claim racing the commit can still surface
                import logging

                logging.getLogger(__name__).warning(
                    "csi claim failed at plan commit: volume=%s alloc=%s",
                    vid,
                    alloc.id,
                )

    def csi_release(self, index: int, volume_id: str, alloc_id: str) -> bool:
        with self._lock:
            import copy as _copy

            table = self._own("csi_volumes")
            vol = table.get(volume_id)
            if vol is None:
                return False
            vol = _copy.deepcopy(vol)
            if not vol.release(alloc_id):
                return False
            vol.modify_index = index
            table[volume_id] = vol
            self._bump(index, "csi_volumes")
            return True

    def _update_deployment_status_locked(
        self, index: int, deployment_id: str, status: str, desc: str
    ) -> None:
        import copy as _copy

        table = self._own("deployments")
        d = table.get(deployment_id)
        if d is None:
            return
        if not d.active() and status in ("paused", "running"):
            # a pause/resume that raced a terminal transition must not
            # resurrect the deployment (deployment_endpoint.go rejects
            # state changes on terminal deployments; the applier-side
            # guard makes the race benign for every submitter)
            return
        d2 = _copy.deepcopy(d)
        d2.status = status
        d2.status_description = desc
        d2.modify_index = index
        table[deployment_id] = d2

    def update_deployment_status(
        self, index: int, deployment_id: str, status: str, desc: str = ""
    ) -> None:
        with self._lock:
            self._update_deployment_status_locked(index, deployment_id, status, desc)
            self._bump(index, "deployments")

    def update_deployment(self, index: int, deployment) -> None:
        """Replace a deployment record (watcher count refresh)."""
        with self._lock:
            table = self._own("deployments")
            existing = table.get(deployment.id)
            if (
                existing is not None
                and not existing.active()
                and deployment.active()
            ):
                # a replace flipping a TERMINAL deployment back to active
                # can only be a racing pause/resume or a stale watcher
                # refresh — refuse the resurrection (the endpoint-side
                # active() check is advisory; this guard is authoritative)
                self._bump(index, "deployments")
                return
            deployment.modify_index = index
            table[deployment.id] = deployment
            self._bump(index, "deployments")

    def update_alloc_health(
        self, index: int, healthy_ids: list[str], unhealthy_ids: list[str]
    ) -> None:
        """Set AllocDeploymentStatus health verdicts
        (UpsertDeploymentAllocHealth in the reference)."""
        import copy as _copy
        import time as _t

        from ..structs.deployment import AllocDeploymentStatus

        with self._lock:
            table = self._own("allocs")
            for ids, verdict in ((healthy_ids, True), (unhealthy_ids, False)):
                for aid in ids:
                    a = table.get(aid)
                    if a is None:
                        continue
                    a2 = _copy.copy(a)
                    a2.deployment_status = AllocDeploymentStatus(
                        healthy=verdict,
                        timestamp_unix=_t.time(),
                        canary=a.canary,
                    )
                    a2.modify_index = index
                    table[aid] = a2
            self._bump(index, "allocs")

    def update_allocs_desired_transition(
        self, index: int, transitions: dict[str, object]
    ) -> None:
        """Set DesiredTransition per alloc (the drainer's migrate marks —
        state_store.go UpdateAllocsDesiredTransitions)."""
        import copy as _copy

        with self._lock:
            table = self._own("allocs")
            for aid, tr in transitions.items():
                a = table.get(aid)
                if a is None:
                    continue
                a2 = _copy.copy(a)
                a2.desired_transition = tr
                a2.modify_index = index
                table[aid] = a2
            self._bump(index, "allocs")

    # -- ACL ---------------------------------------------------------------
    def upsert_acl_policies(self, index: int, policies: Iterable) -> None:
        with self._lock:
            table = self._own("acl_policies")
            for p in policies:
                existing = table.get(p.name)
                p.create_index = existing.create_index if existing else index
                p.modify_index = index
                table[p.name] = p
            self._bump(index, "acl_policies")

    def delete_acl_policies(self, index: int, names: Iterable[str]) -> None:
        with self._lock:
            table = self._own("acl_policies")
            for name in names:
                table.pop(name, None)
            self._bump(index, "acl_policies")

    def upsert_acl_tokens(self, index: int, tokens: Iterable) -> None:
        with self._lock:
            table = self._own("acl_tokens")
            by_secret = self._own("acl_tokens_by_secret")
            for t in tokens:
                existing = table.get(t.accessor_id)
                if existing is not None:
                    t.create_index = existing.create_index
                    if existing.secret_id != t.secret_id:
                        by_secret.pop(existing.secret_id, None)
                else:
                    t.create_index = index
                t.modify_index = index
                table[t.accessor_id] = t
                by_secret[t.secret_id] = t.accessor_id
            self._bump(index, "acl_tokens")

    def delete_acl_tokens(self, index: int, accessor_ids: Iterable[str]) -> None:
        with self._lock:
            table = self._own("acl_tokens")
            by_secret = self._own("acl_tokens_by_secret")
            for aid in accessor_ids:
                t = table.pop(aid, None)
                if t is not None:
                    by_secret.pop(t.secret_id, None)
            self._bump(index, "acl_tokens")

    def bootstrap_acl_token(self, index: int, token) -> None:
        """One-shot bootstrap (acl_endpoint.go Bootstrap): guarded by the
        acl_bootstrap index sentinel."""
        with self._lock:
            if self._t.indexes.get("acl_bootstrap", 0) > 0:
                raise PermissionError("ACL bootstrap already done")
            table = self._own("acl_tokens")
            by_secret = self._own("acl_tokens_by_secret")
            token.create_index = index
            token.modify_index = index
            table[token.accessor_id] = token
            by_secret[token.secret_id] = token.accessor_id
            idx = self._own("indexes")
            idx["acl_bootstrap"] = index
            self._bump(index, "acl_tokens")

    # -- scheduler config --------------------------------------------------
    def set_scheduler_config(self, index: int, cfg: SchedulerConfiguration) -> None:
        with self._lock:
            self._t.scheduler_config = cfg
            self._bump(index, "scheduler_config")

    # -- namespaces (nomad/state namespace table) --------------------------
    def upsert_namespace(self, index: int, ns) -> None:
        with self._lock:
            table = self._own("namespaces")
            existing = table.get(ns.name)
            ns.create_index = existing.create_index if existing else index
            ns.modify_index = index
            table[ns.name] = ns
            self._bump(index, "namespaces")

    def delete_namespace(self, index: int, name: str) -> None:
        """Refuses deletion of a non-empty namespace (namespace_endpoint.go
        DeleteNamespaces: namespaces with jobs cannot be removed)."""
        with self._lock:
            if name == "default":
                raise ValueError("default namespace cannot be deleted")
            if name not in self._t.namespaces:
                raise KeyError(f"namespace not found: {name}")
            in_use = [
                jid for (jns, jid) in self._t.jobs if jns == name
            ]
            if in_use:
                raise ValueError(
                    f"namespace {name!r} has {len(in_use)} job(s); "
                    "deregister them first"
                )
            table = self._own("namespaces")
            del table[name]
            self._bump(index, "namespaces")

    def restore_namespace(self, ns) -> None:
        with self._lock:
            self._own("namespaces")[ns.name] = ns
            self._latest_index = max(self._latest_index, ns.modify_index)

    # -- scaling events (structs.JobScalingEvents) -------------------------
    MAX_SCALING_EVENTS = 20

    def add_scaling_event(self, index: int, namespace: str, job_id: str,
                          event: dict) -> None:
        with self._lock:
            table = self._own("scaling_events")
            key = (namespace, job_id)
            event = {**event, "index": index}
            table[key] = ((event,) + table.get(key, ()))[
                : self.MAX_SCALING_EVENTS
            ]
            self._bump(index, "scaling_events")
