"""L0 durable-state layer: MVCC store, snapshots, persistence."""

from .store import SchedulerConfiguration, StateSnapshot, StateStore

__all__ = ["StateStore", "StateSnapshot", "SchedulerConfiguration"]
