"""State snapshot persistence — checkpoint/resume of the whole cluster
state.

Reference: nomadFSM.Snapshot/Restore with 21 typed record streams
(nomad/fsm.go:36-59) + ``operator snapshot save/restore``
(helper/snapshot). Here the snapshot is a versioned pickle of the store's
tables (the record types are plain dataclasses); the format carries a
magic + version header so future migrations can dispatch.
"""

from __future__ import annotations

import os
import pickle

SNAPSHOT_MAGIC = b"NOMADTPU-SNAP"
SNAPSHOT_VERSION = 1


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` atomically (tmp + fsync + rename + dir
    fsync): a crash mid-write leaves either the old file or the new one,
    never a torn mix."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dirfd = os.open(os.path.dirname(os.path.abspath(path)) or ".", os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def save_snapshot(store, path: str) -> int:
    """Serialize a consistent snapshot; returns the index it captured."""
    snap = store.snapshot()
    payload = {
        "version": SNAPSHOT_VERSION,
        "index": snap.index,
        "nodes": dict(snap._t.nodes),
        "jobs": dict(snap._t.jobs),
        "job_versions": dict(snap._t.job_versions),
        "evals": dict(snap._t.evals),
        "allocs": dict(snap._t.allocs),
        "deployments": dict(snap._t.deployments),
        "acl_policies": dict(snap._t.acl_policies),
        "acl_tokens": dict(snap._t.acl_tokens),
        "acl_bootstrap": snap._t.indexes.get("acl_bootstrap", 0),
        "csi_volumes": dict(snap._t.csi_volumes),
        "namespaces": dict(snap._t.namespaces),
        "scaling_events": dict(snap._t.scaling_events),
        "scheduler_config": snap._t.scheduler_config,
    }
    # Atomic replace: never truncate the previous good snapshot. A crash
    # mid-write must leave either the old snapshot or the new one — the WAL
    # prefix behind the old snapshot is compacted, so a torn write here
    # would permanently lose committed state (helper/snapshot does the
    # same tmp+rename dance in the reference).
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(SNAPSHOT_MAGIC)
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dirfd = os.open(os.path.dirname(os.path.abspath(path)) or ".", os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)
    return snap.index


def restore_snapshot(path: str):
    """Rebuild a StateStore from a snapshot file (indexes re-derived)."""
    from .store import StateStore

    with open(path, "rb") as f:
        magic = f.read(len(SNAPSHOT_MAGIC))
        if magic != SNAPSHOT_MAGIC:
            raise ValueError(f"{path} is not a nomad-tpu snapshot")
        # snapshot blobs arrive over the wire too (Raft InstallSnapshot) —
        # deserialize through the framework allowlist, not bare pickle
        from ..rpc.framing import restricted_loads

        payload = restricted_loads(f.read())
    if payload["version"] != SNAPSHOT_VERSION:
        raise ValueError(f"unsupported snapshot version {payload['version']}")

    store = StateStore()
    index = max(payload["index"], 1)
    for node in payload["nodes"].values():
        store.upsert_node(index, node)
    # jobs: preserve versions (upsert_job would re-version)
    with store._lock:
        jobs = store._own("jobs")
        jobs.update(payload["jobs"])
        versions = store._own("job_versions")
        versions.update(payload["job_versions"])
        store._bump(index, "jobs", "job_versions")
    store.upsert_evals(index, list(payload["evals"].values()))
    store.upsert_allocs(index, list(payload["allocs"].values()))
    for d in payload["deployments"].values():
        store.upsert_deployment(index, d)
    if payload.get("acl_policies"):
        store.upsert_acl_policies(index, list(payload["acl_policies"].values()))
    if payload.get("acl_tokens"):
        store.upsert_acl_tokens(index, list(payload["acl_tokens"].values()))
    if payload.get("acl_bootstrap"):
        with store._lock:
            store._own("indexes")["acl_bootstrap"] = payload["acl_bootstrap"]
    for vol in payload.get("csi_volumes", {}).values():
        store.restore_csi_volume(vol)
    for ns in payload.get("namespaces", {}).values():
        store.restore_namespace(ns)
    if payload.get("scaling_events"):
        with store._lock:
            store._own("scaling_events").update(payload["scaling_events"])
    store.set_scheduler_config(index, payload["scheduler_config"])
    store._latest_index = max(store._latest_index, payload["index"])
    return store
