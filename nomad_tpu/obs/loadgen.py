"""Steady-state load generator — sustained churn instead of
drain-to-empty.

``build_schedule`` turns (seed, seconds, rate, …) into a deterministic
event timeline: Poisson job arrivals at the target rate, rolling job
updates and stops against already-arrived jobs, and node drains/flaps
with paired restore events. The schedule is a pure function of its
arguments — per-stream seeded rngs exactly like the chaos plane's
``build_schedule`` — so two soaks with the same seed plan byte-identical
traffic no matter what the cluster does with it.

``run_soak`` boots a cluster (multi-worker lanes on when
``batch_workers > 1``), seeds the node fleet, attaches an
:class:`~nomad_tpu.obs.slo.SloCollector`, replays the schedule on the
wall clock, quiesces, checks every cluster invariant, and returns a
:class:`SoakRun` whose ``canonical()`` follows the chaos-report
discipline: config + schedule + targets + report schema are
bit-reproducible; measured latencies are timing-dependent diagnostics.

``saturation_search`` binary-searches the arrival rate for the highest
rate at which the p99 eval-latency SLO still holds and the queue keeps
up — the ``saturation_rate`` headline in BENCH files.
"""

from __future__ import annotations

import json
import random
import time
from typing import Optional

from ..chaos.invariants import InvariantReport, check_cluster, metrics_baseline
from ..chaos.runner import _quiesce
from .slo import SLO_SCHEMA, SloCollector, SloTargets, build_report

DEFAULT_NODES = 200
# broker redelivery scaled for a soak run (production default is 60 s —
# longer than the whole soak, so recovery paths would never run)
RUN_UNACK_TIMEOUT = 5.0
RUN_NACK_DELAY = 0.1
RUN_INITIAL_NACK_DELAY = 0.05


class SoakEvent:
    """One planned traffic event. ``row()`` is the canonical rendering
    used in reports and determinism tests."""

    __slots__ = ("t", "kind", "target", "count", "priority")

    def __init__(
        self, t: float, kind: str, target: int,
        count: int = 0, priority: int = 0,
    ):
        self.t = t
        self.kind = kind          # arrive|update|stop|drain|undrain|down|up
        self.target = target      # job seq or node index
        self.count = count
        self.priority = priority

    def row(self) -> str:
        extra = ""
        if self.kind in ("arrive", "update"):
            extra = f" count={self.count} prio={self.priority}"
        return f"{self.t:8.3f}s {self.kind} #{self.target}{extra}"


def build_schedule(
    seed: int,
    seconds: float,
    rate: float,
    nodes: int,
    update_frac: float = 0.3,
    stop_frac: float = 0.1,
    drain_rate: float = 0.05,
    flap_rate: float = 0.05,
    spike_rate: float = 0.0,
    spike_start: float = 0.0,
    spike_seconds: float = 0.0,
    priority_mix: Optional[dict] = None,
) -> list[SoakEvent]:
    """Deterministic soak timeline. Independent seeded streams per
    event family (the chaos plane's per-site rng pattern) keep each
    family's draws stable when another family's knob changes.

    ``spike_rate > 0`` layers a burst arrival stream (its own
    ``{seed}:spike`` rng) on the Poisson base during
    ``[spike_start, spike_start + spike_seconds)`` — the reproducible
    overload scenario. ``priority_mix`` maps priority → weight for
    arrival priorities (both streams); ``None`` keeps the classic
    uniform 30/50/70 draw byte-identical to earlier releases."""
    events: list[SoakEvent] = []

    if priority_mix:
        # keys may arrive as strings (JSON / --priority-mix on the CLI)
        _by_prio = {int(p): float(w) for p, w in priority_mix.items()}
        _prios = tuple(sorted(_by_prio))
        _weights = [_by_prio[p] for p in _prios]

        def _prio(rng: random.Random) -> int:
            return rng.choices(_prios, weights=_weights)[0]

    else:

        def _prio(rng: random.Random) -> int:
            return rng.choice((30, 50, 70))

    arr = random.Random(f"{seed}:arrivals")
    t = 0.0
    seq = 0
    while True:
        t += arr.expovariate(rate) if rate > 0 else seconds
        if t >= seconds:
            break
        events.append(
            SoakEvent(
                t, "arrive", seq,
                count=arr.randint(1, 3),
                priority=_prio(arr),
            )
        )
        seq += 1
    arrivals = seq

    churn = random.Random(f"{seed}:churn")
    if arrivals:
        for kind, frac in (("update", update_frac), ("stop", stop_frac)):
            n = int(round(arrivals * frac))
            for _ in range(n):
                ct = churn.uniform(1.0, seconds) if seconds > 1.0 else 0.0
                # target a job that has (deterministically) arrived by
                # ct: idempotent registers make a miss harmless anyway
                arrived_by = max(
                    1, sum(1 for e in events
                           if e.kind == "arrive" and e.t < ct)
                )
                events.append(
                    SoakEvent(
                        ct, kind, churn.randrange(arrived_by),
                        count=churn.randint(1, 4), priority=50,
                    )
                )

    nodestream = random.Random(f"{seed}:nodes")
    for kind, restore, nrate in (
        ("drain", "undrain", drain_rate),
        ("down", "up", flap_rate),
    ):
        t = 0.0
        while nrate > 0:
            t += nodestream.expovariate(nrate)
            if t >= seconds:
                break
            idx = nodestream.randrange(nodes)
            dur = nodestream.uniform(1.0, 3.0)
            events.append(SoakEvent(t, kind, idx))
            events.append(SoakEvent(t + dur, restore, idx))

    # burst stream LAST so the base arrivals, churn targeting, and node
    # streams above draw identically whether or not a spike is layered
    # on (same per-family isolation the chaos plane guarantees)
    if spike_rate > 0 and spike_seconds > 0:
        spike = random.Random(f"{seed}:spike")
        spike_end = min(seconds, spike_start + spike_seconds)
        t = spike_start
        while True:
            t += spike.expovariate(spike_rate)
            if t >= spike_end:
                break
            events.append(
                SoakEvent(
                    t, "arrive", seq,
                    count=spike.randint(1, 3),
                    priority=_prio(spike),
                )
            )
            seq += 1

    events.sort(key=lambda e: (e.t, e.kind, e.target))
    return events


class SoakRun:
    """Result of one soak: canonical config/schedule + measured SLOs."""

    def __init__(
        self,
        seed: int,
        seconds: float,
        rate: float,
        nodes: int,
        batch_workers: int,
        schedule_rows: list[str],
        targets: SloTargets,
        slo: dict,
        report: InvariantReport,
        workload: dict,
        duration_s: float,
        saturation_rate: Optional[float] = None,
        admission: Optional[dict] = None,
        incremental: bool = False,
    ):
        self.seed = seed
        self.seconds = seconds
        self.rate = rate
        self.nodes = nodes
        self.batch_workers = batch_workers
        self.schedule_rows = schedule_rows
        self.targets = targets
        self.slo = slo
        self.report = report
        self.workload = workload
        self.duration_s = duration_s
        self.saturation_rate = saturation_rate
        # measured controller snapshot + recovered/conserved flags
        # (diagnostics — never part of canonical())
        self.admission = admission
        # whether the incremental score cache was on for the run — a
        # config axis, so it belongs in canonical(): on/off arms of an
        # A/B differ byte-for-byte exactly here
        self.incremental = incremental

    @property
    def ok(self) -> bool:
        """Invariants clean — the hard gate. The SLO verdict is its own
        signal under ``slo["verdict"]``."""
        return self.report.ok

    def canonical(self) -> dict:
        """The bit-reproducible part: pure function of the soak
        arguments plus the pinned report schema. Measured latencies,
        queue depths and counters depend on wall-clock interleaving and
        are reported separately as diagnostics."""
        return {
            "seed": self.seed,
            "seconds": self.seconds,
            "rate": self.rate,
            "nodes": self.nodes,
            "batch_workers": self.batch_workers,
            "incremental": self.incremental,
            "schedule": list(self.schedule_rows),
            "targets": self.targets.to_dict(),
            "slo_schema": list(SLO_SCHEMA),
        }

    def canonical_json(self) -> str:
        return json.dumps(self.canonical(), sort_keys=True, indent=2)

    def to_dict(self) -> dict:
        d = self.canonical()
        d["slo"] = self.slo
        d["saturation_rate"] = self.saturation_rate
        d["invariants"] = self.report.to_dict()
        d["admission"] = self.admission
        d["workload"] = dict(self.workload)
        d["duration_s"] = round(self.duration_s, 3)
        d["ok"] = self.ok
        return d

    def render(self, verbose: bool = False) -> str:
        v = self.slo.get("verdict", {})
        lines = [
            f"soak: seed={self.seed} {self.seconds:g}s rate={self.rate:g}/s "
            f"nodes={self.nodes} batch_workers={self.batch_workers} "
            f"events={len(self.schedule_rows)}",
            "workload: "
            + " ".join(f"{k}={v2}" for k, v2 in sorted(self.workload.items())),
        ]
        ev = self.slo["eval_latency_ms"]
        pl = self.slo["placement_latency_ms"]
        q = self.slo["queue_depth"]
        t = self.slo["throughput"]
        lines.append(
            f"eval latency   p50={ev['p50_ms']:.2f}ms "
            f"p95={ev['p95_ms']:.2f}ms p99={ev['p99_ms']:.2f}ms "
            f"max={ev['max_ms']:.2f}ms (n={ev['count']})"
        )
        lines.append(
            f"placement      p50={pl['p50_ms']:.2f}ms "
            f"p95={pl['p95_ms']:.2f}ms p99={pl['p99_ms']:.2f}ms "
            f"max={pl['max_ms']:.2f}ms (n={pl['count']})"
        )
        lines.append(
            f"queue depth    mean={q['mean']:.1f} max={q['max']:.0f} "
            f"over {q['seconds']}s"
        )
        lines.append(
            f"throughput     arrivals={t['arrivals']} "
            f"({t['arrival_rate_per_s']}/s) completions={t['completions']} "
            f"({t['completion_rate_per_s']}/s)"
        )
        ctr = self.slo["counters"]
        nonzero = " ".join(
            f"{k}={int(ctr[k])}" for k in sorted(ctr) if ctr[k]
        )
        lines.append("counters       " + (nonzero or "(all zero)"))
        if self.admission is not None:
            tiers = self.admission.get("counters", {})
            decided = " ".join(
                f"{tier}={c['admitted']}/{c['deferred']}/{c['shed']}"
                for tier, c in sorted(tiers.items())
                if c["submitted"]
            )
            lines.append(
                f"admission      level={self.admission.get('level')} "
                f"recovered={self.admission.get('recovered')} "
                f"conserved={self.admission.get('conserved')} "
                + (f"adm/def/shed {decided}" if decided else "(no decisions)")
            )
        if self.saturation_rate is not None:
            lines.append(f"saturation_rate {self.saturation_rate:g}/s")
        lines.append("invariants:")
        lines.append(self.report.render())
        lines.append(
            ("SLO PASS" if v.get("pass") else
             "SLO FAIL: " + "; ".join(v.get("failures", ())))
        )
        lines.append("PASS" if self.ok else "FAIL")
        if verbose:
            lines.append(f"-- diagnostics ({self.duration_s:.2f}s) --")
            for k, val in sorted(self.report.info.items()):
                lines.append(f"  {k}: {val}")
        return "\n".join(lines)


def _build_node(i: int):
    from .. import mock

    return mock.node(id=f"soak-node-{i:05d}", name=f"soak-node-{i:05d}")


def _build_job(seq: int, count: int, priority: int):
    from .. import mock
    from ..structs import Resources, Task, TaskGroup

    j = mock.job(id=f"soak-job-{seq:05d}", name=f"soak-job-{seq:05d}")
    j.priority = priority
    j.task_groups = [
        TaskGroup(
            name="web",
            count=count,
            tasks=[
                Task(
                    name="web",
                    driver="exec",
                    resources=Resources(cpu=256, memory_mb=128),
                )
            ],
        )
    ]
    return j


def _apply_event(server, ev: SoakEvent, node_ids: list[str], counts: dict):
    from ..server.admission import AdmissionRejected
    from ..structs.node import DrainStrategy

    try:
        if ev.kind == "arrive":
            server.register_job(_build_job(ev.target, ev.count, ev.priority))
            counts["arrivals"] += 1
            return True
        if ev.kind == "update":
            server.register_job(_build_job(ev.target, ev.count, ev.priority))
            counts["updates"] += 1
            return True
        if ev.kind == "stop":
            server.deregister_job("default", f"soak-job-{ev.target:05d}")
            counts["stops"] += 1
            return False
        node_id = node_ids[ev.target]
        if ev.kind == "drain":
            server.update_node_drain(node_id, DrainStrategy(deadline_s=30.0))
            counts["drains"] += 1
        elif ev.kind == "undrain":
            server.update_node_drain(node_id, None)
        elif ev.kind == "down":
            server.update_node_status(node_id, "down")
            counts["flaps"] += 1
        elif ev.kind == "up":
            server.update_node_status(node_id, "ready")
        return False
    except AdmissionRejected:
        # overload pushback (429-equivalent): the submission never
        # entered the cluster — counted separately from plain rejects
        # so the overload soak can assert the throttle actually fired
        counts["throttled"] += 1
        return False
    except Exception:
        # a stop against a never-registered job or a drain racing a
        # deregister: real clients see the same errors and move on
        counts["rejected"] += 1
        return False


def run_soak(
    seed: int = 7,
    seconds: float = 5.0,
    rate: float = 20.0,
    nodes: int = DEFAULT_NODES,
    batch_workers: int = 1,
    targets: Optional[SloTargets] = None,
    update_frac: float = 0.3,
    stop_frac: float = 0.1,
    drain_rate: float = 0.05,
    flap_rate: float = 0.05,
    quiesce_timeout: float = 60.0,
    saturation: bool = False,
    saturation_kwargs: Optional[dict] = None,
    spike_rate: float = 0.0,
    spike_start: float = 0.0,
    spike_seconds: float = 0.0,
    priority_mix: Optional[dict] = None,
    admission_overrides: Optional[dict] = None,
    calibration_artifact: Optional[str] = None,
) -> SoakRun:
    """One full soak cycle: boot, seed fleet, replay the schedule on
    the wall clock, quiesce, check invariants, build the SLO report."""
    from ..server.server import Server, ServerConfig
    from ..utils.backend import incremental_enabled

    targets = targets or SloTargets()
    schedule = build_schedule(
        seed, seconds, rate, nodes,
        update_frac=update_frac, stop_frac=stop_frac,
        drain_rate=drain_rate, flap_rate=flap_rate,
        spike_rate=spike_rate, spike_start=spike_start,
        spike_seconds=spike_seconds, priority_mix=priority_mix,
    )
    baseline = metrics_baseline()
    t_start = time.perf_counter()
    server = Server(
        ServerConfig(
            num_workers=batch_workers,
            num_batch_workers=batch_workers,
            # no clients heartbeat in-process; node liveness is driven
            # by the schedule's down/up events instead
            heartbeat_ttl=3600.0,
            admission_overrides=admission_overrides,
            # probe-derived thresholds (bench.py soak --saturation
            # writes the artifact; this run admits under them)
            calibration_artifact=calibration_artifact,
        )
    )
    broker = server.eval_broker
    broker.unack_timeout = RUN_UNACK_TIMEOUT
    broker.nack_delay = RUN_NACK_DELAY
    broker.initial_nack_delay = RUN_INITIAL_NACK_DELAY
    counts = {
        "arrivals": 0, "updates": 0, "stops": 0,
        "drains": 0, "flaps": 0, "rejected": 0, "throttled": 0,
    }
    collector = SloCollector()
    report: InvariantReport
    try:
        server.establish_leadership()
        node_ids = []
        for i in range(nodes):
            node = _build_node(i)
            # setup, not the measured path: seed the fleet directly
            # into state exactly like bench.build_cluster
            server.store.upsert_node(i + 1, node)
            node_ids.append(node.id)
        collector.start(server)
        try:
            t0 = time.perf_counter()
            restores: list[SoakEvent] = []
            for ev in schedule:
                lag = ev.t - (time.perf_counter() - t0)
                if lag > 0:
                    time.sleep(lag)
                if _apply_event(server, ev, node_ids, counts):
                    collector.note_arrival()
                if ev.kind in ("undrain", "up"):
                    restores = [
                        r for r in restores
                        if not (r.kind == ev.kind and r.target == ev.target)
                    ]
                elif ev.kind in ("drain", "down"):
                    restores.append(
                        SoakEvent(
                            0.0,
                            "undrain" if ev.kind == "drain" else "up",
                            ev.target,
                        )
                    )
            # end of soak: restore any node still drained/down so the
            # cluster quiesces to a fully-ready fleet (the paired
            # restore events past the horizon never fired)
            seen = set()
            for r in restores:
                if (r.kind, r.target) in seen:
                    continue
                seen.add((r.kind, r.target))
                _apply_event(server, r, node_ids, counts)
            quiesced = _quiesce(server, quiesce_timeout)
            # bounded-recovery check: after the traffic (and any spike)
            # ends and the queue drains, the controller must step back
            # to NORMAL within the p99 window's retention (spike-era
            # samples keep voting for up to 2x window_s after drain)
            # plus one dwell per level and slack
            adm = server.admission
            win = getattr(adm._p99_window, "window_s", 0.0) or 0.0
            recovery_deadline = time.perf_counter() + (
                2.0 * win + 3.0 * adm.dwell_s + 2.0
            )
            recovered = adm.level(force=True) == "normal"
            while not recovered and time.perf_counter() < recovery_deadline:
                time.sleep(0.05)
                recovered = adm.level(force=True) == "normal"
            admission = adm.snapshot()
            admission["recovered"] = recovered
            admission["conserved"] = adm.conserved()
        finally:
            collector.stop()
        report = check_cluster(server, plane=None, baseline=baseline)
        report.info["quiesced"] = quiesced
        report.info["batch_workers"] = batch_workers
        report.info["admission_recovered"] = recovered
        if not quiesced:
            report._fail(
                "eval_terminal",
                "quiesce",
                f"cluster failed to quiesce within {quiesce_timeout}s",
            )
        slo = build_report(collector, targets)
    finally:
        try:
            server.shutdown()
        except Exception:
            from ..utils.metrics import count_swallowed

            count_swallowed("soak", None)
    sat = None
    if saturation:
        sat = saturation_search(
            seed=seed, batch_workers=batch_workers,
            **(saturation_kwargs or {}),
        )
    return SoakRun(
        seed=seed,
        seconds=seconds,
        rate=rate,
        nodes=nodes,
        batch_workers=batch_workers,
        schedule_rows=[e.row() for e in schedule],
        targets=targets,
        slo=slo,
        report=report,
        workload=counts,
        duration_s=time.perf_counter() - t_start,
        saturation_rate=sat,
        admission=admission,
        incremental=incremental_enabled(),
    )


def saturation_search(
    seed: int = 7,
    nodes: int = 200,
    batch_workers: int = 1,
    probe_seconds: float = 2.0,
    lo: float = 4.0,
    hi: float = 128.0,
    iterations: int = 5,
    targets: Optional[SloTargets] = None,
    log=None,
) -> float:
    """Binary search for the highest sustainable arrival rate: p99 eval
    latency under target AND the queue keeps up (completions ≥ 80% of
    arrivals by quiesce — a saturated broker leaves a growing backlog).
    Probes are short steady-state soaks with node churn disabled, so
    the knob under test is the arrival rate alone. Returns the highest
    rate that passed (``lo`` if even that saturates)."""
    targets = targets or SloTargets()

    def sustainable(rate: float) -> bool:
        run = run_soak(
            seed=seed, seconds=probe_seconds, rate=rate, nodes=nodes,
            batch_workers=batch_workers, targets=targets,
            update_frac=0.0, stop_frac=0.0,
            drain_rate=0.0, flap_rate=0.0,
            quiesce_timeout=max(10.0, probe_seconds * 5),
        )
        ev = run.slo["eval_latency_ms"]
        t = run.slo["throughput"]
        latency_ok = (
            ev["count"] == 0
            or targets.eval_p99_ms is None
            or ev["p99_ms"] <= targets.eval_p99_ms
        )
        keeping_up = (
            t["arrivals"] == 0
            or t["completions"] >= 0.8 * t["arrivals"]
        )
        ok = latency_ok and keeping_up and run.report.ok
        if log:
            log(
                f"saturation probe rate={rate:g}/s p99={ev['p99_ms']:.1f}ms "
                f"completions={t['completions']}/{t['arrivals']} "
                f"-> {'ok' if ok else 'saturated'}"
            )
        return ok

    best = lo
    if not sustainable(lo):
        return lo
    if sustainable(hi):
        return hi
    for _ in range(iterations):
        mid = (lo + hi) / 2.0
        if sustainable(mid):
            best = mid
            lo = mid
        else:
            hi = mid
    return round(best, 3)
