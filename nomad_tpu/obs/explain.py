"""Placement explainability — score provenance from the dense kernels.

The reference answers "why did alloc X land on node Y" with the
per-node iterator chain's AllocMetric/ScoreMetaData trail (structs.go
:10034-10079): every node the stack walked leaves a score row the CLI
renders. Our batched kernels (device/score.py) collapse that walk into
one dense pass and return only the winning rows, so the trail has to be
*reconstructed* from the same component math instead of recorded along
the way.

This module is that reconstruction — the one seam raw score data may
cross on its way to an operator (lint rule NTA014 polices the
scheduler/server side). Three pieces:

- ``PlacementExplanation``: per-group top-k candidate nodes with the
  per-component score breakdown (fit, anti-affinity, reschedule
  penalty, affinity, spread boost, throughput), a feasibility-rejection
  histogram bucketed by structured reason, and the committed placement
  rows.
- ``explain_group`` / ``explain_hetero_group``: host-side NumPy mirrors
  of the kernels' component semantics (the same math as
  ``device.score._rescore_pick``, which the conflict-repair walk
  already trusts as the exact oracle). Explanations are *observational*:
  they never feed back into placement, so explain-on and explain-off
  place bit-identically, and no new jitted program exists in either
  mode (zero extra retraces by construction).
- ``finalize_explanations``: post-repair pass that stamps the
  *committed* rows (conflict repair may move placements after the
  kernel returns) and derives per-instance score breakdowns by
  replaying the lane's placements against a usage overlay.

Candidate ranking is computed against the same base usage snapshot the
kernel pass scored against, so on an uncontended pass the top-1
candidate is exactly the node greedy placement committed first — the
provenance property the parity tests pin across seeds and algorithms.
Decorrelated batch passes add per-lane tie-break jitter (~1e-5) the
explanation deliberately omits: the ranking shown is the jitter-free
score, while ``placed_nodes`` always reflects what actually committed.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..structs.alloc import NodeScoreMeta
from ..structs.resources import BINPACK_MAX_SCORE, RESOURCE_DIMS

EXPLAIN_SCHEMA_VERSION = 1
DEFAULT_TOP_K = 5

# structured feasibility-rejection reasons (the histogram keys). A node
# lands in exactly one of ineligible/class-infeasible/distinct-hosts,
# or in one-or-more exhausted:* axis buckets (a node short on cpu AND
# memory counts in both, matching AllocMetric.dimension_exhausted).
REJECT_INELIGIBLE = "ineligible"
REJECT_CLASS_INFEASIBLE = "class-infeasible"
REJECT_DISTINCT_HOSTS = "distinct-hosts"
REJECT_PENALTY = "penalty-excluded"


def _exhausted_key(dim: str) -> str:
    return f"exhausted:{dim}"


@dataclass
class CandidateExplanation:
    """One candidate node's first-instance score breakdown."""

    node_id: str = ""
    node_row: int = -1
    final_score: float = 0.0
    components: dict[str, float] = field(default_factory=dict)
    # committed instances of this group on this node (filled post-repair)
    placed: int = 0


@dataclass
class PlacementExplanation:
    """Why one task group's placements landed where they did.

    Threaded onto ``PlacementResult.explanation`` by the placement
    kernels when explain is on, stamped into ``failed_tg_allocs`` for
    unplaced groups and the flight recorder's explanation ring for
    placed ones (scheduler/generic.py, scheduler/system.py)."""

    schema_version: int = EXPLAIN_SCHEMA_VERSION
    job_id: str = ""
    tg_name: str = ""
    algorithm: str = ""
    policy: str = ""  # hetero policy name when the joint pass scored
    nodes_evaluated: int = 0
    feasible_nodes: int = 0
    top_candidates: list[CandidateExplanation] = field(default_factory=list)
    rejections: dict[str, int] = field(default_factory=dict)
    # committed node ids in placement order (post conflict repair)
    placed_nodes: list[str] = field(default_factory=list)
    # CP solver provenance when the cp-pack joint pass scored
    # (scheduler/cp.py): {"iterations", "gap", "agreement"}. None for
    # every other algorithm — the JSON shape only grows a "cp" block
    # when the solver ran, so existing schema pins are untouched.
    cp: dict | None = None
    # gang provenance when the cp-gang pass scored a gang member
    # (scheduler/cp.py): {"gang_id", "members", "topology_score",
    # "release_rounds"}. None otherwise — same only-grows contract.
    gang: dict | None = None


def _feasibility(capacity, used, a, n: int, throughputs=None):
    """Shared feasibility split: returns (fits bool[n], rejections dict).

    Bucketing mirrors the kernels' gates in order: eligibility, the
    hetero class gate (tp==0 ⇒ the job cannot progress on that class),
    distinct_hosts, then per-resource-axis capacity — a node is counted
    under the FIRST gate that rejects it, except the axis buckets which
    count every short dimension (AllocMetric.dimension_exhausted
    semantics, rank.go:483)."""
    elig = np.asarray(a.eligible[:n], dtype=bool)
    rejections: dict[str, int] = {}
    n_inelig = int(n - elig.sum())
    if n_inelig:
        rejections[REJECT_INELIGIBLE] = n_inelig

    alive = elig.copy()
    if throughputs is not None:
        class_dead = alive & (np.asarray(throughputs[:n]) <= 0.0)
        k = int(class_dead.sum())
        if k:
            rejections[REJECT_CLASS_INFEASIBLE] = k
        alive &= ~class_dead
    if a.distinct_hosts:
        dh_dead = alive & (np.asarray(a.job_counts[:n]) > 0)
        k = int(dh_dead.sum())
        if k:
            rejections[REJECT_DISTINCT_HOSTS] = k
        alive &= ~dh_dead

    prop = used[:n] + a.ask[None, :]
    short = prop > capacity[:n]  # [n, D]
    for d, dim in enumerate(RESOURCE_DIMS):
        k = int((alive & short[:, d]).sum())
        if k:
            rejections[_exhausted_key(dim)] = k
    fits_cap = ~short.any(axis=1)
    if a.slot_caps is not None:
        dev_dead = alive & fits_cap & (np.asarray(a.slot_caps[:n]) < 1)
        k = int(dev_dead.sum())
        if k:
            rejections[_exhausted_key("devices")] = k
        alive &= ~dev_dead
    fits = alive & fits_cap
    # reschedule-penalized nodes are feasible but score -1 on that
    # component; surfaced in the histogram because in practice they are
    # excluded from winning whenever any unpenalized node fits
    if fits.any():
        k = int((fits & np.asarray(a.penalty_nodes[:n], dtype=bool)).sum())
        if k:
            rejections[REJECT_PENALTY] = k
    return fits, rejections


def _final_vector(
    capacity, used, a, n: int, fits, counts, algorithm_spread,
    throughputs=None, desired_total=None, rows=None,
):
    """Vectorized first-instance final score f32[n] (-inf infeasible) —
    the ranking pass. Same formulation as device.score._rescore_pick
    (the host oracle conflict repair already trusts) so the candidate
    order agrees with what greedy placement picks.

    ``rows`` (i64[m], ascending) restricts the pass to a candidate
    subset — the sharded-node-axis path, where pulling full score rows
    back to host would defeat the mesh; the returned vector is then
    length m, aligned with ``rows``, and ``fits`` must already be
    row-aligned."""
    from ..device.score import (
        BLOCK_DISTINCT_CAP,
        _host_block_tables,
    )

    idx = slice(None, n) if rows is None else rows
    m = n if rows is None else len(rows)
    prop = used[idx] + a.ask[None, :]
    free = np.where(
        capacity[idx] > 0,
        (capacity[idx] - prop) / np.maximum(capacity[idx], 1e-9),
        1.0,
    )
    pow_sum = 10.0 ** free[:, 0] + 10.0 ** free[:, 1]
    binpack = np.clip(20.0 - pow_sum, 0.0, BINPACK_MAX_SCORE)
    spread_fit = np.clip(pow_sum - 2.0, 0.0, BINPACK_MAX_SCORE)
    fit = (spread_fit if algorithm_spread else binpack) / BINPACK_MAX_SCORE
    jc = np.asarray(a.job_counts)[idx]
    coll = jc.astype(np.float32)
    dt = a.desired_total if desired_total is None else desired_total
    anti = np.where(jc > 0, -(coll + 1.0) / max(dt, 1.0), 0.0)
    pen = np.asarray(a.penalty_nodes, dtype=bool)[idx]
    resched = np.where(pen, -1.0, 0.0)
    aff = a.affinity_scores[idx] if a.has_affinities else 0.0
    boost = np.zeros(m, dtype=np.float32)
    has_spread_any = False
    if a.blocks is not None and counts is not None:
        tbl_boost, _allow = _host_block_tables(counts, a.blocks)
        for b in range(a.blocks.num_blocks):
            if a.blocks.kinds[b] == BLOCK_DISTINCT_CAP:
                continue
            has_spread_any = True
            vids = a.blocks.value_ids[b][idx]
            safe = np.maximum(vids, 0)
            boost += np.where(vids >= 0, tbl_boost[b][safe], -1.0)
    spread_on = has_spread_any & (boost != 0.0)
    num = fit + anti + resched + aff + np.where(spread_on, boost, 0.0)
    den = (
        1.0
        + (jc > 0)
        + pen
        + (1.0 if a.has_affinities else 0.0)
        + spread_on
    )
    if throughputs is not None:
        tp = np.asarray(throughputs)[idx]
        num = num + tp
        den = den + 1.0
    return np.where(fits, num / den, -np.inf)


def _components_at(
    capacity, used, a, rows, placed_on_rows, counts, algorithm_spread,
    throughputs=None, desired_total=None,
):
    """Per-component breakdown for ``rows`` (same math and component
    join rules as device.score._rescore_pick / component_scores).
    ``placed_on_rows`` is this lane's prior instance count per row (0
    for the first-instance candidate view). Returns a list of
    (components dict, final) aligned with rows."""
    from ..device.score import (
        BLOCK_DISTINCT_CAP,
        _host_block_tables,
    )

    fit_name = "spread-fit" if algorithm_spread else "binpack"
    blocks = a.blocks
    boost_tbl = None
    if blocks is not None and counts is not None:
        boost_tbl, _allow = _host_block_tables(counts, blocks)
    out = []
    for row, mine in zip(rows, placed_on_rows):
        prop = used[row] + a.ask
        free = np.where(
            capacity[row] > 0,
            (capacity[row] - prop) / np.maximum(capacity[row], 1e-9),
            1.0,
        )
        pow_sum = 10.0 ** float(free[0]) + 10.0 ** float(free[1])
        binpack = float(np.clip(20.0 - pow_sum, 0.0, BINPACK_MAX_SCORE))
        spread_fit = float(np.clip(pow_sum - 2.0, 0.0, BINPACK_MAX_SCORE))
        fit = (spread_fit if algorithm_spread else binpack) / BINPACK_MAX_SCORE
        comps = {fit_name: fit}
        num, den = fit, 1.0
        jc = int(a.job_counts[row]) + int(mine)
        if jc > 0:
            dt = a.desired_total if desired_total is None else desired_total
            anti = -(jc + 1.0) / max(dt, 1.0)
            comps["job-anti-affinity"] = anti
            num, den = num + anti, den + 1.0
        if a.penalty_nodes[row]:
            comps["node-reschedule-penalty"] = -1.0
            num, den = num - 1.0, den + 1.0
        if a.has_affinities:
            aff = float(a.affinity_scores[row])
            comps["node-affinity"] = aff
            num, den = num + aff, den + 1.0
        if blocks is not None and boost_tbl is not None:
            boost = 0.0
            spread_any = False
            for b in range(blocks.num_blocks):
                if blocks.kinds[b] == BLOCK_DISTINCT_CAP:
                    continue
                spread_any = True
                v = blocks.value_ids[b, row]
                boost += float(boost_tbl[b][v]) if v >= 0 else -1.0
            if spread_any and boost != 0.0:
                comps["allocation-spread"] = boost
                num, den = num + boost, den + 1.0
        if throughputs is not None:
            tp = float(throughputs[row])
            comps["throughput"] = tp
            num, den = num + tp, den + 1.0
        out.append((comps, num / den))
    return out


def explain_group(
    cluster,
    a,
    used0,
    *,
    algorithm: str = "binpack",
    algorithm_spread: bool = False,
    throughputs=None,
    top_k: int = DEFAULT_TOP_K,
    desired_total=None,
    candidate_rows=None,
) -> PlacementExplanation:
    """Build the candidate/rejection explanation for one group ask
    against the usage snapshot the kernel pass scored with.

    ``throughputs`` is the pre-normalized [0, 1] heterogeneity axis when
    the *scoring* path consumed one (score_group); the base placement
    kernels ignore the axis, so their explanations do too.

    ``candidate_rows`` (ascending node rows) restricts the RANKING pass
    to the columns the kernel's hierarchical top-k already surfaced —
    the node-axis-sharded path, where the per-shard top-k union provably
    contains every global winner, so ranking the union ranks the same
    top candidates without gathering full score rows to host. The
    rejection histogram stays a full host-side pass either way (it reads
    the flattened ask masks, not device score rows)."""
    n = cluster.num_nodes
    capacity = np.asarray(cluster.capacity)
    used = np.asarray(used0)
    fits, rejections = _feasibility(capacity, used, a, n, throughputs)
    ex = PlacementExplanation(
        job_id=a.job_id,
        tg_name=a.tg_name,
        algorithm=algorithm,
        nodes_evaluated=n,
        feasible_nodes=int(fits.sum()),
        rejections=rejections,
    )
    if not fits.any() or a.count <= 0:
        return ex
    counts = a.blocks.counts0 if a.blocks is not None else None
    if candidate_rows is not None:
        rows = np.asarray(candidate_rows, dtype=np.int64)
        rows = np.unique(rows[(rows >= 0) & (rows < n)])
        if rows.size == 0:
            return ex
        finals = _final_vector(
            capacity, used, a, n, fits[rows], counts, algorithm_spread,
            throughputs, desired_total, rows=rows,
        )
        # stable sort over ascending rows: ties keep row order, matching
        # argmax's first-index win (the subset inherits the full
        # ranking's tie-break because rows are ascending)
        pick = np.argsort(-finals, kind="stable")[: max(top_k, 1)]
        pick = pick[finals[pick] > -np.inf]
        order = rows[pick]
        finals_by_row = {int(r): finals[i] for i, r in enumerate(rows)}
        finals = np.full(n, -np.inf, dtype=np.float32)
        for r, f in finals_by_row.items():
            finals[r] = f
    else:
        finals = _final_vector(
            capacity, used, a, n, fits, counts, algorithm_spread,
            throughputs, desired_total,
        )
        # stable sort: ties keep row order, matching argmax's
        # first-index win
        order = np.argsort(-finals, kind="stable")[: max(top_k, 1)]
        order = order[finals[order] > -np.inf]
    breakdown = _components_at(
        capacity, used, a, order, np.zeros(len(order)), counts,
        algorithm_spread, throughputs, desired_total,
    )
    ex.top_candidates = [
        CandidateExplanation(
            node_id=cluster.node_ids[int(r)],
            node_row=int(r),
            final_score=float(f),
            components={k: float(v) for k, v in comps.items()},
        )
        for r, (comps, f) in zip(order, breakdown)
    ]
    return ex


def explain_hetero_group(
    cluster,
    a,
    used0,
    *,
    policy: str,
    tp_row,
    tpmax: float,
    cost,
    top_k: int = DEFAULT_TOP_K,
) -> PlacementExplanation:
    """Explanation for one lane of the joint hetero pass. Candidates
    rank by the policy's node key (throughput for maxmin/makespan,
    throughput-per-cost for cost — scheduler/hetero.py _node_keys) so
    the top candidate is the node the joint greedy takes first; the
    reported score stays the tp-share in [0, 1] like PlacementResult."""
    n = cluster.num_nodes
    capacity = np.asarray(cluster.capacity)
    used = np.asarray(used0)
    fits, rejections = _feasibility(capacity, used, a, n, tp_row)
    ex = PlacementExplanation(
        job_id=a.job_id,
        tg_name=a.tg_name,
        algorithm=f"hetero-{policy}",
        policy=policy,
        nodes_evaluated=n,
        feasible_nodes=int(fits.sum()),
        rejections=rejections,
    )
    if not fits.any() or a.count <= 0:
        return ex
    tp = np.asarray(tp_row[:n], dtype=np.float64)
    cost_n = np.asarray(cost[:n], dtype=np.float64)
    key = tp / np.maximum(cost_n, 1e-9) if policy == "cost" else tp
    key = np.where(fits, key, -np.inf)
    order = np.argsort(-key, kind="stable")[: max(top_k, 1)]
    order = order[key[order] > -np.inf]
    denom = max(float(tpmax), 1e-9)
    for r in order:
        comps = {"throughput": float(tp[r] / denom)}
        if policy == "cost":
            comps["cost"] = float(cost_n[r])
            comps["throughput-per-cost"] = float(key[r])
        ex.top_candidates.append(
            CandidateExplanation(
                node_id=cluster.node_ids[int(r)],
                node_row=int(r),
                final_score=float(tp[r] / denom),
                components=comps,
            )
        )
    return ex


def explain_cp_group(
    cluster,
    a,
    used0,
    *,
    scores_row,
    cp: dict | None = None,
    top_k: int = DEFAULT_TOP_K,
) -> PlacementExplanation:
    """Explanation for one group of the joint CP pass (scheduler/cp.py).
    Candidates rank by the group's dense score row — the relaxation's
    objective coefficients, i.e. the node the fractional assignment
    weights highest comes first — and the solver-level provenance
    (iterations, duality-gap proxy, rounded-vs-fractional agreement)
    rides in the ``cp`` block. Stays on the non-hetero finalize path
    (``policy`` empty): per-instance breakdowns replay the same binpack
    component math the score row was built from."""
    n = cluster.num_nodes
    capacity = np.asarray(cluster.capacity)
    used = np.asarray(used0)
    fits, rejections = _feasibility(capacity, used, a, n)
    ex = PlacementExplanation(
        job_id=a.job_id,
        tg_name=a.tg_name,
        algorithm="cp-pack",
        nodes_evaluated=n,
        feasible_nodes=int(fits.sum()),
        rejections=rejections,
        cp=dict(cp) if cp is not None else None,
    )
    if not fits.any() or a.count <= 0:
        return ex
    key = np.where(fits, np.asarray(scores_row[:n], dtype=np.float64),
                   -np.inf)
    order = np.argsort(-key, kind="stable")[: max(top_k, 1)]
    order = order[key[order] > -np.inf]
    for r in order:
        ex.top_candidates.append(
            CandidateExplanation(
                node_id=cluster.node_ids[int(r)],
                node_row=int(r),
                final_score=float(key[r]),
                components={"score-matrix": float(key[r])},
            )
        )
    return ex


def explain_cp_gang(
    cluster,
    a,
    used0,
    *,
    scores_row,
    cp: dict | None = None,
    gang_info: dict | None = None,
    top_k: int = DEFAULT_TOP_K,
) -> PlacementExplanation:
    """Explanation for one group of the cp-gang joint pass: the
    cp-pack explanation plus gang provenance — which gang the group
    belongs to, its member set, the signed topology score its final
    placement achieved, and how many auction rounds the all-or-nothing
    gate held its wins back (release_rounds)."""
    ex = explain_cp_group(
        cluster, a, used0, scores_row=scores_row, cp=cp, top_k=top_k
    )
    ex.algorithm = "cp-gang"
    if gang_info is not None:
        ex.gang = dict(gang_info)
    return ex


def _instance_components_vec(capacity, used0, a, rows, mine, algorithm_spread):
    """Vectorized per-instance breakdowns for one lane's committed rows —
    the blocks-free fast path of the finalize replay. Instance i on row
    r sees ``used0[r] + mine[i] * ask``, the same state the sequential
    overlay would hold when it scored that instance. Returns
    (components, final) pairs aligned with ``rows``."""
    fit_name = "spread-fit" if algorithm_spread else "binpack"
    rows = np.asarray(rows, dtype=np.int64)
    mine_i = np.asarray(mine, dtype=np.int64)
    cap = capacity[rows]
    prop = used0[rows] + (mine_i + 1).astype(np.float32)[:, None] * a.ask[None, :]
    free = np.where(cap > 0, (cap - prop) / np.maximum(cap, 1e-9), 1.0)
    pow_sum = 10.0 ** free[:, 0] + 10.0 ** free[:, 1]
    binpack = np.clip(20.0 - pow_sum, 0.0, BINPACK_MAX_SCORE)
    spread_fit = np.clip(pow_sum - 2.0, 0.0, BINPACK_MAX_SCORE)
    fit = (spread_fit if algorithm_spread else binpack) / BINPACK_MAX_SCORE
    jc = np.asarray(a.job_counts)[rows] + mine_i
    anti = np.where(jc > 0, -(jc + 1.0) / max(a.desired_total, 1.0), 0.0)
    pen = np.asarray(a.penalty_nodes, dtype=bool)[rows]
    num = fit + anti + np.where(pen, -1.0, 0.0)
    den = 1.0 + (jc > 0) + pen
    aff = None
    if a.has_affinities:
        aff = np.asarray(a.affinity_scores)[rows]
        num = num + aff
        den = den + 1.0
    finals = num / den
    out = []
    for i in range(len(rows)):
        comps = {fit_name: float(fit[i])}
        if jc[i] > 0:
            comps["job-anti-affinity"] = float(anti[i])
        if pen[i]:
            comps["node-reschedule-penalty"] = -1.0
        if aff is not None:
            comps["node-affinity"] = float(aff[i])
        out.append((comps, float(finals[i])))
    return out


def finalize_explanations(cluster, asks, results, used_override=None) -> None:
    """Post-repair pass: stamp committed rows into each lane's
    explanation and derive per-instance score breakdowns by replaying
    the lane's placements against a lane-local usage overlay (the same
    evolution the greedy scan applied). Conflict repair mutates
    ``node_rows`` in place after the kernel returned, so this runs
    AFTER ``repair_batch_conflicts`` — ``placed_nodes`` reflects what
    will actually commit."""
    used0 = np.asarray(
        cluster.used if used_override is None else used_override
    )
    capacity = np.asarray(cluster.capacity)
    for a, res in zip(asks, results):
        ex = getattr(res, "explanation", None)
        if ex is None:
            continue
        hetero = bool(ex.policy)
        rows_list = np.asarray(res.node_rows).tolist()
        placed_on: dict[int, int] = {}
        ex.placed_nodes = []
        if not hetero and a.blocks is None:
            # fast path: no spread counts evolve per placement, so every
            # instance's state is used0 + (prior instances on its row) *
            # ask — computable for the whole lane in one vectorized pass
            placed_idx = [i for i, r in enumerate(rows_list) if r >= 0]
            prows = [rows_list[i] for i in placed_idx]
            mine = []
            for r in prows:
                mine.append(placed_on.get(r, 0))
                placed_on[r] = placed_on.get(r, 0) + 1
            instance_meta = [None] * len(rows_list)
            if prows:
                breakdown = _instance_components_vec(
                    capacity, used0, a, prows, mine,
                    ex.algorithm == "spread",
                )
                for i, r, (comps, final) in zip(
                    placed_idx, prows, breakdown
                ):
                    node_id = cluster.node_ids[r]
                    ex.placed_nodes.append(node_id)
                    instance_meta[i] = NodeScoreMeta(
                        node_id=node_id, scores=comps, norm_score=final
                    )
        else:
            used = used0.copy()
            counts = (
                a.blocks.counts0.copy() if a.blocks is not None else None
            )
            instance_meta = []
            for i, row in enumerate(rows_list):
                if row < 0:
                    instance_meta.append(None)
                    continue
                node_id = cluster.node_ids[row]
                ex.placed_nodes.append(node_id)
                if hetero:
                    comps = {"throughput": float(res.scores[i])}
                    final = float(res.scores[i])
                else:
                    (comps, final), = _components_at(
                        capacity, used, a, [row],
                        [placed_on.get(row, 0)], counts,
                        ex.algorithm == "spread",
                    )
                instance_meta.append(
                    NodeScoreMeta(
                        node_id=node_id,
                        scores={k: float(v) for k, v in comps.items()},
                        norm_score=float(final),
                    )
                )
                used[row] += a.ask
                placed_on[row] = placed_on.get(row, 0) + 1
                if counts is not None:
                    for b in range(a.blocks.num_blocks):
                        v = a.blocks.value_ids[b, row]
                        if v >= 0:
                            counts[b, v] += 1
        # per-instance metas ride as a plain attribute (not a dataclass
        # field) so API encodings of the explanation stay bounded
        ex.instance_meta = instance_meta
        by_row = {c.node_row: c for c in ex.top_candidates}
        for row, k in placed_on.items():
            cand = by_row.get(row)
            if cand is not None:
                cand.placed = k
            else:
                # repair (or a later greedy step) committed a node
                # outside the first-instance top-k: append it so
                # `alloc why` always finds its breakdown
                meta = next(
                    m
                    for m in instance_meta
                    if m is not None and m.node_id == cluster.node_ids[row]
                )
                ex.top_candidates.append(
                    CandidateExplanation(
                        node_id=meta.node_id,
                        node_row=int(row),
                        final_score=meta.norm_score,
                        components=dict(meta.scores),
                        placed=k,
                    )
                )


def score_meta_for_row(
    cluster, a, used0, row: int, *, algorithm_spread: bool = False,
    desired_total=None,
) -> NodeScoreMeta:
    """First-instance breakdown for one committed row — the system
    scheduler's per-alloc ScoreMetaData (a system job places at most one
    alloc per node, so the first-instance view IS the instance view).
    Normalizes the heterogeneity axis exactly like score_group so the
    throughput component matches the recorded final."""
    throughputs = None
    if a.has_throughputs and a.throughputs is not None:
        tp = np.asarray(a.throughputs, dtype=np.float32)
        best = float(np.max(np.where(a.eligible, tp, 0.0)))
        if best > 0.0:
            throughputs = tp / np.float32(best)
    counts = a.blocks.counts0 if a.blocks is not None else None
    ((comps, final),) = _components_at(
        np.asarray(cluster.capacity),
        np.asarray(used0),
        a,
        [int(row)],
        [0],
        counts,
        algorithm_spread,
        throughputs,
        desired_total,
    )
    return NodeScoreMeta(
        node_id=cluster.node_ids[int(row)],
        scores={k: float(v) for k, v in comps.items()},
        norm_score=float(final),
    )


def candidates_as_score_meta(ex: PlacementExplanation) -> list[NodeScoreMeta]:
    """Top-k candidates as AllocMetric.score_meta rows (the reference's
    ScoreMetaData shape) — stamped onto failed placements so blocked
    evals carry the near-miss table."""
    return [
        NodeScoreMeta(
            node_id=c.node_id,
            scores=dict(c.components),
            norm_score=c.final_score,
        )
        for c in ex.top_candidates
    ]


def explanation_to_dict(ex: PlacementExplanation) -> dict:
    """JSON shape for the API/CLI surfaces (schema pinned by the tier-1
    smoke test)."""
    return {
        "schema_version": ex.schema_version,
        "job_id": ex.job_id,
        "tg_name": ex.tg_name,
        "algorithm": ex.algorithm,
        "policy": ex.policy,
        "nodes_evaluated": ex.nodes_evaluated,
        "feasible_nodes": ex.feasible_nodes,
        "top_candidates": [
            {
                "node_id": c.node_id,
                "rank": i + 1,
                "final_score": c.final_score,
                "components": dict(c.components),
                "placed": c.placed,
            }
            for i, c in enumerate(ex.top_candidates)
        ],
        "rejections": dict(ex.rejections),
        "placed_nodes": list(ex.placed_nodes),
        **({"cp": dict(ex.cp)} if ex.cp is not None else {}),
        **({"gang": dict(ex.gang)} if ex.gang is not None else {}),
    }
