"""nomad_tpu.obs — zero-dependency tracing, profiling and SLOs.

Four parts (see trace.py / recorder.py / slo.py / loadgen.py and
utils/backend.py):

- **Spans**: ``global_tracer`` keys one trace tree per eval id and
  carries it across the worker → plan-queue → applier thread handoff.
- **Kernel profiling**: ``utils/backend.traced_jit`` reports per-kernel
  wall time, compile events and the abstract shapes that triggered them,
  attached to the enclosing span when one is active.
- **Flight recorder**: ``flight_recorder`` rings the last N completed
  traces + error events, surfaced at ``/v1/agent/trace`` and rendered by
  the ``nomad-tpu trace`` CLI.
- **SLO plane**: ``SloCollector`` windows eval/placement latency from
  the recorder's trace feed into bounded histograms; ``run_soak``
  replays a seeded Poisson traffic schedule against a live cluster and
  reports against declared ``SloTargets`` (``/v1/agent/slo``,
  ``nomad-tpu slo report``, ``bench.py soak``).
- **Calibration plane**: ``CalibrationTable`` gives every operational
  constant a provenance (``default``/``probe``/``learned``);
  ``ThroughputEstimator`` learns per-(device class × job profile)
  throughputs from the recorder's trace feed (``/v1/agent/calibration``,
  ``nomad-tpu calibrate``, ``bench.py calib``).
"""

# calibrate imports before loadgen: loadgen pulls in the server stack,
# which lazily re-enters obs — calibrate must already be importable
from .calibrate import (
    CalibrationTable,
    ThroughputEstimator,
    calibration_overview,
    derive_admission_thresholds,
    global_estimator,
    global_table,
    run_calib_ab,
    write_probe_artifact,
)
from .loadgen import SoakRun, build_schedule, run_soak, saturation_search
from .recorder import (
    FlightRecorder,
    flight_recorder,
    phase_breakdown,
    render_trace,
    trace_latencies,
)
from .slo import (
    SLO_SCHEMA,
    SloCollector,
    SloTargets,
    build_report,
    live_report,
    slo_schema_of,
)
from .trace import Span, SpanContext, Tracer, global_tracer

__all__ = [
    "CalibrationTable",
    "FlightRecorder",
    "SLO_SCHEMA",
    "SloCollector",
    "SloTargets",
    "SoakRun",
    "Span",
    "SpanContext",
    "ThroughputEstimator",
    "Tracer",
    "build_report",
    "build_schedule",
    "calibration_overview",
    "derive_admission_thresholds",
    "flight_recorder",
    "global_estimator",
    "global_table",
    "global_tracer",
    "live_report",
    "phase_breakdown",
    "render_trace",
    "run_calib_ab",
    "run_soak",
    "saturation_search",
    "slo_schema_of",
    "trace_latencies",
    "write_probe_artifact",
]
