"""nomad_tpu.obs — zero-dependency tracing + profiling.

Three parts (see trace.py / recorder.py and utils/backend.py):

- **Spans**: ``global_tracer`` keys one trace tree per eval id and
  carries it across the worker → plan-queue → applier thread handoff.
- **Kernel profiling**: ``utils/backend.traced_jit`` reports per-kernel
  wall time, compile events and the abstract shapes that triggered them,
  attached to the enclosing span when one is active.
- **Flight recorder**: ``flight_recorder`` rings the last N completed
  traces + error events, surfaced at ``/v1/agent/trace`` and rendered by
  the ``nomad-tpu trace`` CLI.
"""

from .recorder import (
    FlightRecorder,
    flight_recorder,
    phase_breakdown,
    render_trace,
)
from .trace import Span, SpanContext, Tracer, global_tracer

__all__ = [
    "FlightRecorder",
    "Span",
    "SpanContext",
    "Tracer",
    "flight_recorder",
    "global_tracer",
    "phase_breakdown",
    "render_trace",
]
