"""SLO plane: windowed latency collection and per-run SLO reports.

The drain benches answer "how fast does a backlog empty"; the SLO plane
answers the production question — what are p99 eval and placement
latency *under sustained load*, is the queue stable, and did the
resilience machinery stay quiet. Three pieces:

* :class:`SloTargets` — declared service-level objectives. Every field
  set to ``None`` is unchecked; everything else feeds the pass/fail
  verdict.
* :class:`SloCollector` — a flight-recorder listener (sees every
  completed trace, even the ones the 256-trace ring evicts) feeding
  bounded log-bucketed histograms, plus a 1 Hz sampler thread filling
  per-second rings with broker queue depth. O(buckets + window) memory
  for an arbitrarily long soak.
* :func:`build_report` / :func:`live_report` — the canonical per-run
  SLO report: latency percentiles, queue-depth stats, throughput,
  resilience/lane counters, ring coverage, and the verdict. The report
  *schema* (key paths) is pinned by :data:`SLO_SCHEMA` so regressions
  in the report shape fail tests, while the measured values are
  timing-dependent diagnostics (same canonicalization discipline as
  chaos reports).

Latency definitions (one place, used by both the always-on metrics feed
in ``recorder.py`` and this collector, via ``trace_latencies``):

* eval latency    = broker queue wait (``queue_wait_ms`` on the dequeue
  span) + the trace's own duration (dequeue → ack/nack).
* placement latency = Σ durations of the ``invoke_scheduler`` and
  ``submit_plan`` spans — the schedule-and-commit core, excluding queue
  wait and bookkeeping.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..utils.hist import LogHistogram, TimeSeriesRing
from ..utils.metrics import global_metrics
from .recorder import flight_recorder, trace_latencies

# counters surfaced in every SLO report, report key → metrics key;
# values are windowed deltas against the collector-start baseline
REPORT_COUNTERS = {
    "breaker_trips": "nomad.resilience.trips_total",
    "fallback_activations": "nomad.resilience.fallback_calls",
    "fallback_passes": "nomad.resilience.fallback_passes",
    "lane_conflicts": "nomad.plan.lane_conflicts",
    "cross_lane_handoffs": "nomad.plan.cross_lane_handoffs",
    "lane_handoff_fallbacks": "nomad.worker.lane_handoff_fallbacks",
    "stale_token_drops": "nomad.worker.stale_token_drops",
    "unack_timeouts": "nomad.broker.unack_timeouts",
    "deadline_nacks": "nomad.resilience.eval.deadline_nacks",
    "traces_evicted": "nomad.obs.traces_evicted",
    "admission_deferred": "nomad.admission.deferred_total",
    "admission_shed": "nomad.admission.shed_total",
}

_LATENCY_KEYS = (
    "count", "mean_ms", "p50_ms", "p95_ms", "p99_ms", "max_ms",
)

# the pinned report shape: every key path build_report() emits, in
# sorted order. Structural — a function of the code, never of a run —
# so it belongs in the canonical block of a soak report.
SLO_SCHEMA = tuple(sorted(
    [f"eval_latency_ms.{k}" for k in _LATENCY_KEYS]
    + [f"eval_latency_high_ms.{k}" for k in _LATENCY_KEYS]
    + [f"placement_latency_ms.{k}" for k in _LATENCY_KEYS]
    + [f"plan_apply_ms.{k}" for k in _LATENCY_KEYS]
    + [
        "queue_depth.mean", "queue_depth.max", "queue_depth.seconds",
        "throughput.arrivals", "throughput.arrival_rate_per_s",
        "throughput.completions", "throughput.completion_rate_per_s",
    ]
    + [f"counters.{k}" for k in sorted(REPORT_COUNTERS)]
    + ["counters.swallowed_errors"]
    + [
        "calibration.constants",
        "calibration.probe_sourced",
        "calibration.learned_cells",
        "calibration.estimator_samples",
    ]
    + [
        "device_cache.score_rows_rescored",
        "device_cache.score_rows_reused",
        "device_cache.pipeline_overlap_ms",
    ]
    + [
        "gang.atomic_releases",
        "gang.released_allocs",
        "gang.stopped_allocs",
        "gang.groups_in",
        "gang.commits",
        "gang.kernel_releases",
        "gang.fallback_failures",
    ]
    + [
        "defrag.moves_planned",
        "defrag.moves_completed",
        "defrag.moves_aborted",
        "defrag.moves_interrupted",
        "defrag.moves_recovered",
        "defrag.budget_exhausted_cycles",
        "defrag.capacity_violations",
        "defrag.packing_efficiency",
        "defrag.drain_migrated",
        "defrag.drain_force_stops",
    ]
    + [
        "ring_coverage.traces_recorded",
        "ring_coverage.traces_evicted",
        "ring_coverage.coverage",
        "verdict.pass", "verdict.failures",
    ]
))


def slo_schema_of(slo: dict) -> tuple[str, ...]:
    """Flattened sorted key paths of a measured ``slo`` block — compare
    against :data:`SLO_SCHEMA` to pin the report shape."""
    paths = []
    for k, v in slo.items():
        if isinstance(v, dict):
            paths.extend(f"{k}.{k2}" for k2 in v)
        else:
            paths.append(k)
    return tuple(sorted(paths))


class SloTargets:
    """Declared SLOs. ``None`` disables a check; everything else is
    compared against the measured window in :func:`verdict`."""

    FIELDS = (
        "eval_p99_ms", "high_eval_p99_ms", "placement_p99_ms",
        "queue_depth_max",
        "max_breaker_trips", "max_fallback_activations",
        "max_lane_conflicts", "max_unack_timeouts",
        "max_swallowed_errors", "min_completion_ratio",
    )

    def __init__(
        self,
        eval_p99_ms: Optional[float] = 5000.0,
        high_eval_p99_ms: Optional[float] = None,
        placement_p99_ms: Optional[float] = 2500.0,
        queue_depth_max: Optional[float] = 10000.0,
        max_breaker_trips: Optional[float] = 0.0,
        max_fallback_activations: Optional[float] = 0.0,
        max_lane_conflicts: Optional[float] = 0.0,
        max_unack_timeouts: Optional[float] = None,
        max_swallowed_errors: Optional[float] = None,
        min_completion_ratio: Optional[float] = None,
    ):
        self.eval_p99_ms = eval_p99_ms
        # the overload acceptance bar: high-tier eval latency must hold
        # even while lower tiers are being deferred/shed
        self.high_eval_p99_ms = high_eval_p99_ms
        self.placement_p99_ms = placement_p99_ms
        self.queue_depth_max = queue_depth_max
        self.max_breaker_trips = max_breaker_trips
        self.max_fallback_activations = max_fallback_activations
        self.max_lane_conflicts = max_lane_conflicts
        self.max_unack_timeouts = max_unack_timeouts
        self.max_swallowed_errors = max_swallowed_errors
        self.min_completion_ratio = min_completion_ratio

    def to_dict(self) -> dict:
        return {f: getattr(self, f) for f in self.FIELDS}

    @classmethod
    def from_dict(cls, d: dict) -> "SloTargets":
        return cls(**{f: d[f] for f in cls.FIELDS if f in d})

    def verdict(self, slo: dict) -> dict:
        """Compare a measured ``slo`` block against the targets. Each
        breach is one human-readable failure row; pass ⇔ no rows.
        Latency targets are only enforced once the window actually
        measured something (count > 0) — an empty window is a harness
        bug surfaced elsewhere, not an SLO pass."""
        failures: list[str] = []

        def _over(label: str, measured: float, bound: Optional[float]):
            if bound is not None and measured > bound:
                failures.append(f"{label} {measured:.3f} > {bound:.3f}")

        ev = slo["eval_latency_ms"]
        pl = slo["placement_latency_ms"]
        if ev["count"]:
            _over("eval_p99_ms", ev["p99_ms"], self.eval_p99_ms)
        hi = slo.get("eval_latency_high_ms")
        if hi and hi["count"]:
            _over("high_eval_p99_ms", hi["p99_ms"], self.high_eval_p99_ms)
        if pl["count"]:
            _over(
                "placement_p99_ms", pl["p99_ms"], self.placement_p99_ms
            )
        _over(
            "queue_depth_max", slo["queue_depth"]["max"],
            self.queue_depth_max,
        )
        c = slo["counters"]
        _over("breaker_trips", c["breaker_trips"], self.max_breaker_trips)
        _over(
            "fallback_activations", c["fallback_activations"],
            self.max_fallback_activations,
        )
        _over("lane_conflicts", c["lane_conflicts"], self.max_lane_conflicts)
        _over("unack_timeouts", c["unack_timeouts"], self.max_unack_timeouts)
        _over(
            "swallowed_errors", c["swallowed_errors"],
            self.max_swallowed_errors,
        )
        if self.min_completion_ratio is not None:
            t = slo["throughput"]
            if t["arrivals"]:
                ratio = t["completions"] / t["arrivals"]
                if ratio < self.min_completion_ratio:
                    failures.append(
                        f"completion_ratio {ratio:.3f} < "
                        f"{self.min_completion_ratio:.3f}"
                    )
        return {"pass": not failures, "failures": failures}


class SloCollector:
    """Windowed SLO measurement over a live server.

    ``attach()`` subscribes to the flight recorder (every completed
    trace feeds the latency histograms); ``start(server)`` additionally
    runs a sampler thread that polls broker queue depth once per
    ``period``. All state is bounded: two histograms + fixed rings.
    """

    def __init__(
        self,
        recorder=flight_recorder,
        metrics=global_metrics,
        clock=time.time,
        window_seconds: int = 900,
        period: float = 1.0,
    ):
        self._recorder = recorder
        self._metrics = metrics
        self._clock = clock
        self.period = period
        self._lock = threading.Lock()
        self.eval_hist = LogHistogram()
        # high-priority tier only (tier_of(priority) == "high", from the
        # worker's priority trace tag): the overload story promises this
        # histogram stays within SLO while lower tiers shed
        self.eval_high_hist = LogHistogram()
        self.placement_hist = LogHistogram()
        self.queue_ring = TimeSeriesRing(window_seconds)
        self.arrival_ring = TimeSeriesRing(window_seconds)
        self.completion_ring = TimeSeriesRing(window_seconds)
        self.arrivals = 0
        self.completions = 0
        self._counters_base = dict(metrics.snapshot()["counters"])
        self._hists_base = metrics.histograms()
        self._traces_base = (
            recorder.traces_total, recorder.traces_evicted,
        )
        self._started_at = clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._server = None

    # -- trace feed --------------------------------------------------------
    def attach(self) -> None:
        self._recorder.add_listener(self._on_trace)

    def detach(self) -> None:
        self._recorder.remove_listener(self._on_trace)

    def _on_trace(self, trace: dict) -> None:
        eval_s, placement_s = trace_latencies(trace)
        now = self._clock()
        priority = (trace.get("tags") or {}).get("priority")
        is_high = False
        if priority is not None:
            from ..server.admission import TIER_HIGH, tier_of

            is_high = tier_of(int(priority)) == TIER_HIGH
        with self._lock:
            self.eval_hist.record(eval_s)
            if is_high:
                self.eval_high_hist.record(eval_s)
            if placement_s > 0.0:
                self.placement_hist.record(placement_s)
            self.completions += 1
            self.completion_ring.incr(now)

    def note_arrival(self, n: int = 1) -> None:
        """The load generator calls this per submitted job so arrival
        rate is measured at the same clock as everything else."""
        now = self._clock()
        with self._lock:
            self.arrivals += n
            self.arrival_ring.incr(now, n)

    # -- sampler -----------------------------------------------------------
    def start(self, server=None) -> None:
        self._server = server
        self.attach()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._sample_loop, name="slo-sampler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.detach()
        self.sample_once()  # final depth sample so short windows aren't empty

    def _sample_loop(self) -> None:
        while not self._stop.wait(self.period):
            self.sample_once()

    def sample_once(self) -> None:
        server = self._server
        if server is None:
            return
        try:
            d = server.eval_broker.queue_depths()
            depth = (
                d["ready"] + d["unacked"] + d["delayed"] + d["deferred"]
            )
            plan_depth = server.plan_queue.depth()
        except Exception:
            global_metrics.incr("nomad.slo.sample_errors")
            return
        now = self._clock()
        with self._lock:
            self.queue_ring.observe(now, float(depth + plan_depth))

    def _calibration_block(self) -> dict:
        """Calibration-plane summary for the report: how many constants
        are probe-sourced and how much the throughput estimator has
        learned. Reads the attached server's table/estimator; a
        server-less collector reports the process globals (the shape —
        four scalars — is pinned either way)."""
        from .calibrate import calibration_overview

        return calibration_overview(
            table=getattr(self._server, "calibration", None),
            estimator=getattr(self._server, "throughput_estimator", None),
        )

    def _device_cache_block(self) -> dict:
        """Incremental-rescoring summary for the report: rows served
        from the resident score state vs re-uploaded, and how much
        commit wall time the pipelined loop hid under the next pass.
        Zeros from a server-less collector (the shape — three scalars
        — is pinned either way)."""
        cache = getattr(self._server, "device_cache", None)
        counters = cache.device_counters() if cache is not None else {}
        return {
            "score_rows_rescored": counters.get("score_rows_rescored", 0),
            "score_rows_reused": counters.get("score_rows_reused", 0),
            "pipeline_overlap_ms": counters.get("pipeline_overlap_ms", 0.0),
        }

    # -- report ------------------------------------------------------------
    def measured(self) -> dict:
        """The ``slo`` block: everything measured since the collector
        was constructed, as plain JSON-able data."""
        now = self._clock()
        counters = self._metrics.snapshot()["counters"]
        hists = self._metrics.histograms()
        with self._lock:
            eval_hist = self.eval_hist.copy()
            eval_high_hist = self.eval_high_hist.copy()
            placement_hist = self.placement_hist.copy()
            q = self.queue_ring.stats(now)
            arrivals = self.arrivals
            completions = self.completions
        span = max(now - self._started_at, 1e-9)

        def _delta(metric_key: str) -> float:
            return counters.get(metric_key, 0.0) - self._counters_base.get(
                metric_key, 0.0
            )

        ctr = {
            name: _delta(key) for name, key in REPORT_COUNTERS.items()
        }
        ctr["swallowed_errors"] = sum(
            _delta(k)
            for k in set(counters) | set(self._counters_base)
            if k.endswith(".swallowed_errors")
        )
        plan = hists.get("nomad.plan.apply")
        if plan is not None:
            base = self._hists_base.get("nomad.plan.apply")
            if base is not None:
                plan = plan.diff(base)
        recorded = self._recorder.traces_total - self._traces_base[0]
        evicted = self._recorder.traces_evicted - self._traces_base[1]
        return {
            "eval_latency_ms": eval_hist.snapshot(),
            "eval_latency_high_ms": eval_high_hist.snapshot(),
            "placement_latency_ms": placement_hist.snapshot(),
            "plan_apply_ms": (
                plan.snapshot() if plan is not None
                else LogHistogram().snapshot()
            ),
            "queue_depth": {
                "mean": round(q["mean"], 2),
                "max": q["max"],
                "seconds": q["seconds"],
            },
            "throughput": {
                "arrivals": arrivals,
                "arrival_rate_per_s": round(arrivals / span, 3),
                "completions": completions,
                "completion_rate_per_s": round(completions / span, 3),
            },
            "counters": ctr,
            # gang scheduling health: the atomic-commit seam (scheduler/
            # generic.py, law 15) plus the cp-gang kernel's own ledger —
            # windowed deltas like every other counter in the report
            "gang": {
                "atomic_releases": _delta("nomad.gang.releases"),
                "released_allocs": _delta("nomad.gang.released_allocs"),
                "stopped_allocs": _delta("nomad.gang.stopped_allocs"),
                "groups_in": _delta("nomad.cp.gang_groups_in"),
                "commits": _delta("nomad.cp.gang_commits"),
                "kernel_releases": _delta("nomad.cp.gang_releases"),
                "fallback_failures": _delta(
                    "nomad.cp.gang_fallback_failures"
                ),
            },
            # migration-plane health (server/defrag.py, law 16): the
            # move ledger as windowed deltas, the packing-efficiency
            # gauge as-is, and the drain split — graceful migrations vs
            # deadline force-stops — that the drainer reports
            "defrag": {
                "moves_planned": _delta("nomad.migrate.planned"),
                "moves_completed": _delta("nomad.migrate.completed"),
                "moves_aborted": _delta("nomad.migrate.aborted"),
                "moves_interrupted": _delta("nomad.migrate.interrupted"),
                "moves_recovered": _delta("nomad.migrate.recovered"),
                "budget_exhausted_cycles": _delta(
                    "nomad.migrate.budget_exhausted"
                ),
                "capacity_violations": _delta(
                    "nomad.migrate.capacity_violations"
                ),
                "packing_efficiency": round(
                    self._metrics.snapshot()["gauges"].get(
                        "nomad.migrate.packing_efficiency", 1.0
                    ), 6,
                ),
                "drain_migrated": _delta("nomad.drain.migrated"),
                "drain_force_stops": _delta("nomad.drain.force_stops"),
            },
            "calibration": self._calibration_block(),
            "device_cache": self._device_cache_block(),
            "ring_coverage": {
                "traces_recorded": recorded,
                "traces_evicted": evicted,
                "coverage": round(
                    (recorded - evicted) / recorded, 4
                ) if recorded else 1.0,
            },
        }


def build_report(collector: SloCollector, targets: SloTargets) -> dict:
    """Measured window + verdict: the ``slo`` block of a soak report
    and of ``/v1/agent/slo``."""
    slo = collector.measured()
    slo["verdict"] = targets.verdict(slo)
    return slo


def live_report(server, targets: Optional[SloTargets] = None) -> dict:
    """One-shot SLO report for a live agent (the HTTP endpoint): spin a
    collector against lifetime metrics, take a single queue-depth
    sample, and report the always-on ``nomad.slo.*`` latency series
    recorded by the flight recorder feed since process start."""
    targets = targets or SloTargets()
    collector = SloCollector()
    # lifetime window: zero the baselines so deltas cover process life
    collector._counters_base = {}
    collector._hists_base = {}
    collector._traces_base = (0, 0)
    collector._server = server
    collector.sample_once()
    hists = global_metrics.histograms()
    ev = hists.get("nomad.slo.eval_latency")
    hi = hists.get("nomad.slo.eval_latency_high")
    pl = hists.get("nomad.slo.placement_latency")
    if ev is not None:
        collector.eval_hist = ev
    if hi is not None:
        collector.eval_high_hist = hi
    if pl is not None:
        collector.placement_hist = pl
    collector.completions = collector.eval_hist.count
    slo = build_report(collector, targets)
    return {
        "targets": targets.to_dict(),
        "slo": slo,
        "schema": list(SLO_SCHEMA),
    }
