"""Span/Tracer — per-eval trace trees with cross-thread propagation.

The eval lifecycle crosses three threads (worker → plan-queue → applier,
plus the pipelined commit thread), so a thread-local "current span" alone
cannot carry a trace end to end. The model here:

- A *trace* is keyed by eval id and lives in the tracer's active table
  from ``begin(eval_id)`` (at dequeue) to ``finish(eval_id)`` (at
  ack/nack), whichever thread that happens on.
- ``span(name)`` opens a child of the calling thread's current span and
  times it with ``perf_counter``; ``timer=`` additionally feeds the
  legacy metrics sample of that name, so ``/v1/metrics`` keeps its
  ``nomad.worker.*`` / ``nomad.plan.*`` series while the same interval
  lands in the trace tree (this is what lets eval-lifecycle modules drop
  raw ``metrics.timer`` — lint rule NTA006).
- ``current_ctx()`` → ``attach(ctx)`` is the thread handoff: the worker
  stamps its submit-plan span's context onto the pending plan, the
  applier thread attaches it, and the plan-apply spans parent correctly.
- ``add_span`` records an interval *retroactively* — for phases measured
  before the trace existed (broker dequeue) or shared by a whole batch
  (one device pass scoring 16 evals is recorded into each member's
  trace, tagged ``shared``).

Disabled mode (``set_enabled(False)``) keeps every call a cheap no-op
but ``span(timer=...)`` still feeds the metrics sample — turning tracing
off never changes the metrics surface.

Thread-safety: the active-trace table is mutated only under the tracer
lock (begin/finish); per-trace span lists are appended via the
GIL-atomic ``list.append`` and snapshotted at finish, and completed
traces are handed to the recorder *outside* the lock so the tracer can
never participate in a lock-order cycle with metrics or recorder locks.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager
from typing import Optional

from ..utils.metrics import global_metrics

from .recorder import flight_recorder

_ids = itertools.count(1)


class SpanContext:
    """Immutable handoff token: enough to parent a span from another
    thread (the trace itself stays in the tracer's active table)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id


class Span:
    __slots__ = (
        "trace_id",
        "span_id",
        "parent_id",
        "name",
        "tags",
        "start_unix",
        "duration_ms",
        "status",
        "_t0",
    )

    def __init__(
        self,
        trace_id: str,
        name: str,
        parent_id: Optional[int] = None,
        tags: Optional[dict] = None,
        clock=None,
    ):
        self.trace_id = trace_id
        self.span_id = next(_ids)
        self.parent_id = parent_id
        self.name = name
        self.tags = dict(tags) if tags else {}
        # injectable wall clock (NTA008): the tracer threads its own so
        # estimator/SLO windows over span streams replay under FakeClock
        wall = clock if clock is not None else time.time
        self.start_unix = wall()
        self.duration_ms: Optional[float] = None
        self.status = "ok"
        self._t0 = time.perf_counter()

    def finish(self, status: Optional[str] = None) -> None:
        if self.duration_ms is None:
            self.duration_ms = (time.perf_counter() - self._t0) * 1000.0
        if status is not None:
            self.status = status

    def ctx(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def to_dict(self) -> dict:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_unix": self.start_unix,
            "duration_ms": round(self.duration_ms or 0.0, 4),
            "status": self.status,
            "tags": self.tags,
        }


class _Trace:
    __slots__ = ("trace_id", "root", "spans")

    def __init__(self, trace_id: str, root: Span):
        self.trace_id = trace_id
        self.root = root
        self.spans: list[Span] = [root]


class Tracer:
    def __init__(self, recorder=None, clock=None):
        self._lock = threading.Lock()
        self._active: dict[str, _Trace] = {}
        self._tls = threading.local()
        self._enabled = True
        self._dropped = 0
        self.recorder = recorder
        # wall clock for span start stamps (injectable for FakeClock tests)
        self._clock = clock if clock is not None else time.time

    # -- enable switch -----------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> bool:
        """Flip tracing; disabling drops any in-flight traces (they could
        never finish coherently half-recorded). Returns the old value."""
        with self._lock:
            old = self._enabled
            self._enabled = on
            if not on:
                self._active.clear()
            return old

    def reset(self) -> None:
        with self._lock:
            self._active.clear()
            self._dropped = 0

    # -- trace lifecycle ---------------------------------------------------
    def begin(
        self, trace_id: str, name: str = "eval", tags: Optional[dict] = None
    ) -> Optional[Span]:
        """Open (or return the already-open) trace for ``trace_id``.
        Idempotent so retry paths — a batch-conflict eval re-entering the
        single path — keep appending to the same tree."""
        if not self._enabled:
            return None
        with self._lock:
            tr = self._active.get(trace_id)
            if tr is None:
                tr = _Trace(
                    trace_id,
                    Span(trace_id, name, tags=tags, clock=self._clock),
                )
                self._active[trace_id] = tr
            elif tags:
                tr.root.tags.update(tags)
            return tr.root

    def finish(
        self,
        trace_id: str,
        status: str = "ok",
        error: Optional[str] = None,
    ) -> Optional[dict]:
        """Close the trace and hand the completed tree to the recorder.
        No-op when the trace is unknown (already finished on another
        path, or tracing was off at dequeue)."""
        with self._lock:
            tr = self._active.pop(trace_id, None)
        if tr is None:
            return None
        tr.root.finish(status)
        if error is not None:
            tr.root.tags["error"] = error
        trace = {
            "eval_id": trace_id,
            "status": status,
            "started_at": tr.root.start_unix,
            "duration_ms": round(tr.root.duration_ms or 0.0, 4),
            "tags": tr.root.tags,
            "spans": [s.to_dict() for s in list(tr.spans)],
        }
        if self.recorder is not None:
            self.recorder.record(trace)
        return trace

    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def dropped_spans(self) -> int:
        with self._lock:
            return self._dropped

    # -- thread-local current span ----------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self):
        """Top of this thread's span stack: a Span, or an attached
        SpanContext, or None."""
        st = self._stack()
        return st[-1] if st else None

    def current_ctx(self) -> Optional[SpanContext]:
        cur = self.current()
        if cur is None:
            return None
        if isinstance(cur, SpanContext):
            return cur
        return cur.ctx()

    @contextmanager
    def activate(self, trace_id: str):
        """Make ``trace_id``'s root this thread's current span — the
        commit/worker threads wrap per-eval work in this so spans opened
        downstream (submit_plan, plan_apply) parent into the right tree."""
        tr = self._active.get(trace_id)
        if tr is None:
            yield None
            return
        st = self._stack()
        st.append(tr.root)
        try:
            yield tr.root
        finally:
            self._pop(tr.root)

    @contextmanager
    def attach(self, ctx: Optional[SpanContext]):
        """Adopt a SpanContext from another thread as the current span
        (the applier thread attaches the worker's submit-plan context)."""
        if ctx is None or not self._enabled:
            yield None
            return
        st = self._stack()
        st.append(ctx)
        try:
            yield ctx
        finally:
            self._pop(ctx)

    def _pop(self, item) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is item:
                del st[i]
                return

    # -- spans -------------------------------------------------------------
    @contextmanager
    def span(
        self,
        name: str,
        *,
        parent=None,
        tags: Optional[dict] = None,
        timer: Optional[str] = None,
    ):
        """Time a block as a child span of ``parent`` (default: this
        thread's current span). Yields the Span, or None when no trace is
        active — callers never branch on tracing state. ``timer`` names a
        legacy metrics sample fed unconditionally, tracing on or off."""
        t0 = time.perf_counter()
        sp = self._open(name, parent, tags)
        try:
            yield sp
        except BaseException:
            if sp is not None:
                sp.status = "error"
            raise
        finally:
            dt = time.perf_counter() - t0
            if timer is not None:
                global_metrics.measure(timer, dt)
            if sp is not None:
                sp.duration_ms = dt * 1000.0
                self._pop(sp)

    def _open(self, name, parent, tags) -> Optional[Span]:
        if not self._enabled:
            return None
        if parent is None:
            parent = self.current()
        if parent is None:
            return None
        tr = self._active.get(parent.trace_id)
        if tr is None:
            # trace already finished (late span after ack) — account it
            with self._lock:
                self._dropped += 1
            return None
        sp = Span(
            tr.trace_id, name, parent_id=parent.span_id, tags=tags,
            clock=self._clock,
        )
        tr.spans.append(sp)
        self._stack().append(sp)
        return sp

    def add_span(
        self,
        trace_id: str,
        name: str,
        duration_s: float,
        *,
        parent=None,
        tags: Optional[dict] = None,
    ) -> Optional[Span]:
        """Record an already-measured interval into a trace: the broker
        dequeue (measured before any eval id existed) and batch-shared
        phases (one device pass recorded into each member's tree)."""
        if not self._enabled:
            return None
        tr = self._active.get(trace_id)
        if tr is None:
            with self._lock:
                self._dropped += 1
            return None
        pid = parent.span_id if parent is not None else tr.root.span_id
        sp = Span(trace_id, name, parent_id=pid, tags=tags, clock=self._clock)
        sp.start_unix -= duration_s
        sp.duration_ms = duration_s * 1000.0
        tr.spans.append(sp)
        return sp

    def record_kernel(
        self,
        name: str,
        seconds: float,
        *,
        traced: bool = False,
        shape: Optional[str] = None,
    ) -> Optional[Span]:
        """Attach one jit-kernel call as a child of the calling thread's
        current span (utils/backend hands every traced_jit call here)."""
        cur = self.current()
        if cur is None:
            return None
        tags: dict = {"traced": traced}
        if shape:
            tags["shape"] = shape
        return self.add_span(
            cur.trace_id,
            f"kernel:{name}",
            seconds,
            parent=cur,
            tags=tags,
        )


global_tracer = Tracer(recorder=flight_recorder)
