"""Telemetry-driven calibration plane: learned throughputs, probe-derived
admission thresholds, and constant provenance.

ROADMAP item 5 names two feedback loops that are pure software: Gavel
(arxiv 2008.09213) *assumes* known per-class throughput matrices, yet
the hetero policies (scheduler/hetero.py) run on hand-declared jobspec
coefficients; and the admission controller (server/admission.py) runs
on hand-set threshold constants even though ``saturation_search``
already measures the sustainable rate. This module closes both loops:

* :class:`ThroughputEstimator` — subscribes to the flight-recorder
  listener fan-out (the same seam ``SloCollector`` uses) and maintains
  online per-(device_class × job-profile) throughput estimates from
  observed execute spans: an EMA point estimate anchored by a
  :class:`LogHistogram` of raw rates, per-cell sample counts, and a
  confidence score. Starvation-safe: a cell below the sample floor
  answers with the DECLARED coefficient and reports ``source:
  default`` — estimation degrades to declared, never to garbage.
* :class:`CalibrationTable` — the registry every hand-set constant in
  admission and resilience now routes through. Each entry is a
  :class:`CalibrationConstant` carrying provenance ``{value, source:
  default|probe|learned, samples, window, updated_at_index}``. The
  NTA018 lint bans bare threshold literals outside this module, so a
  constant without provenance can't quietly reappear.
* :func:`derive_admission_thresholds` + the ``CALIB_r01.json`` probe
  artifact — ``bench.py soak --saturation`` persists the measured
  sustainable rate; loading the artifact rewrites the admission
  enter/exit backlog thresholds from Little's law (backlog = rate ×
  tolerated delay) with ``source: probe``.
* :func:`run_calib_ab` — the ``bench.py calib`` gate: rerun the hetero
  A/B with throughputs learned ONLINE from span telemetry (declared
  coefficients hidden from the policies) and require the Gavel wins to
  reproduce within tolerance of the declared run, with
  ``throughput_source=declared`` pinned bit-identical and zero added
  retraces.

Like ``flight_recorder`` and ``global_metrics`` there is one
process-global ``global_table`` / ``global_estimator`` pair; servers
and kernels share them so learned values observed through one seam are
visible at every other.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Callable, Optional

import numpy as np

from ..chaos.plane import chaos_site
from ..utils.hist import LogHistogram
from ..utils.metrics import global_metrics

# -- provenance ---------------------------------------------------------------

SOURCE_DEFAULT = "default"
SOURCE_PROBE = "probe"
SOURCE_LEARNED = "learned"
SOURCES = (SOURCE_DEFAULT, SOURCE_PROBE, SOURCE_LEARNED)

#: canonical name of the persisted saturation-probe artifact
PROBE_ARTIFACT = "CALIB_r01.json"
_PROBE_KIND = "saturation_search"
_PROBE_VERSION = 1


class CalibrationConstant:
    """One tuned constant with provenance. ``default`` is the shipped
    value the entry can always be reset to; ``value`` is what consumers
    read; ``source`` says who set it."""

    __slots__ = ("name", "value", "default", "source", "samples", "window",
                 "updated_at_index")

    def __init__(self, name: str, default: float):
        self.name = name
        self.default = float(default)
        self.value = float(default)
        self.source = SOURCE_DEFAULT
        self.samples = 0
        self.window = ""
        self.updated_at_index = 0

    def to_dict(self) -> dict:
        return {
            "value": self.value,
            "default": self.default,
            "source": self.source,
            "samples": self.samples,
            "window": self.window,
            "updated_at_index": self.updated_at_index,
        }


# The shipped defaults, verbatim from the constants they replace:
# server/admission.py's _DEFAULTS (PR 11) and resilience/breaker.py's
# deadline defaults. This tuple is the ONE place bare threshold numbers
# are allowed to live (NTA018 exempts this module).
DEFAULT_CONSTANTS: tuple[tuple[str, float], ...] = (
    ("admission.brownout_backlog", 512.0),
    ("admission.shed_backlog", 2048.0),
    ("admission.brownout_p99_ms", 2500.0),
    ("admission.shed_p99_ms", 10000.0),
    ("admission.exit_fraction", 0.5),
    ("admission.imbalance_ratio", 1.5),
    ("admission.imbalance_min_backlog", 64.0),
    ("admission.min_p99_samples", 16),
    ("admission.dwell_s", 2.0),
    ("admission.reeval_interval_s", 0.25),
    ("admission.retry_after_s", 2.0),
    ("admission.defer_delay_s", 1.0),
    ("admission.flap_window_s", 0.4),
    ("admission.watermark_fraction.high", 1.0),
    ("admission.watermark_fraction.normal", 0.5),
    ("admission.watermark_fraction.low", 0.25),
    ("admission.brownout_batch_factor", 2),
    ("admission.brownout_batch_timeout_s", 0.4),
    ("admission.shed_cost_quantile", 0.5),
    ("resilience.execute_deadline_s", 5.0),
    ("resilience.compile_deadline_s", 60.0),
)


class CalibrationTable:
    """Thread-safe registry of :class:`CalibrationConstant`. Fixed key
    set (bounded by construction): every constant is declared in
    ``DEFAULT_CONSTANTS``; ``set`` on an unknown name raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {
            name: CalibrationConstant(name, default)
            for name, default in DEFAULT_CONSTANTS
        }
        self._index = 0
        self._probe: Optional[dict] = None

    def get(self, name: str) -> float:
        with self._lock:
            return self._entries[name].value

    def entry(self, name: str) -> dict:
        with self._lock:
            return self._entries[name].to_dict()

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def set(
        self,
        name: str,
        value: float,
        source: str = SOURCE_LEARNED,
        samples: int = 0,
        window: str = "",
    ) -> None:
        if source not in SOURCES:
            raise ValueError(f"unknown calibration source: {source!r}")
        value = float(value)
        if not math.isfinite(value):
            raise ValueError(f"non-finite calibration value for {name}: {value}")
        with self._lock:
            e = self._entries[name]  # KeyError on unknown = the contract
            self._index += 1
            e.value = value
            e.source = source
            e.samples = int(samples)
            e.window = window
            e.updated_at_index = self._index
            global_metrics.incr("nomad.calib.constant_updates")

    def reset(self) -> None:
        """Back to shipped defaults (test isolation for the globals)."""
        with self._lock:
            for e in self._entries.values():
                e.value = e.default
                e.source = SOURCE_DEFAULT
                e.samples = 0
                e.window = ""
                e.updated_at_index = 0
            self._index = 0
            self._probe = None

    def snapshot(self) -> dict:
        with self._lock:
            by_source: dict[str, int] = {s: 0 for s in SOURCES}
            constants = {}
            for name in sorted(self._entries):
                d = self._entries[name].to_dict()
                constants[name] = d
                by_source[d["source"]] += 1
            return {
                "constants": constants,
                "by_source": by_source,
                "probe": dict(self._probe) if self._probe else None,
            }

    # -- consumer views ---------------------------------------------------

    def admission_overrides(self) -> dict:
        """The table's values shaped as ``AdmissionController`` overrides
        — the dict that used to be admission's hand-set ``_DEFAULTS``."""
        with self._lock:
            v = {name: e.value for name, e in self._entries.items()}
        return {
            "brownout_backlog": v["admission.brownout_backlog"],
            "shed_backlog": v["admission.shed_backlog"],
            "brownout_p99_ms": v["admission.brownout_p99_ms"],
            "shed_p99_ms": v["admission.shed_p99_ms"],
            "exit_fraction": v["admission.exit_fraction"],
            "imbalance_ratio": v["admission.imbalance_ratio"],
            "imbalance_min_backlog": v["admission.imbalance_min_backlog"],
            "min_p99_samples": int(v["admission.min_p99_samples"]),
            "dwell_s": v["admission.dwell_s"],
            "reeval_interval_s": v["admission.reeval_interval_s"],
            "retry_after_s": v["admission.retry_after_s"],
            "defer_delay_s": v["admission.defer_delay_s"],
            "flap_window_s": v["admission.flap_window_s"],
            "watermark_fractions": {
                "high": v["admission.watermark_fraction.high"],
                "normal": v["admission.watermark_fraction.normal"],
                "low": v["admission.watermark_fraction.low"],
            },
            "brownout_batch_factor": int(v["admission.brownout_batch_factor"]),
            "brownout_batch_timeout_s": v["admission.brownout_batch_timeout_s"],
            "shed_cost_quantile": v["admission.shed_cost_quantile"],
        }

    def breaker_defaults(self) -> dict:
        """Deadline defaults for ``resilience/breaker.py`` (env vars keep
        precedence over the table at the breaker seam)."""
        with self._lock:
            return {
                "execute_deadline": self._entries[
                    "resilience.execute_deadline_s"
                ].value,
                "compile_deadline": self._entries[
                    "resilience.compile_deadline_s"
                ].value,
            }

    # -- probe artifact ---------------------------------------------------

    def load_probe_artifact(self, artifact) -> int:
        """Ingest a persisted saturation-probe artifact (a path or an
        already-parsed dict, see :func:`write_probe_artifact`) and derive
        the admission enter thresholds from the measured sustainable
        rate. Returns the number of constants rewritten."""
        if isinstance(artifact, (str, bytes)):
            with open(artifact, "r", encoding="utf-8") as f:
                artifact = json.load(f)
        if artifact.get("kind") != _PROBE_KIND:
            raise ValueError(
                f"not a saturation probe artifact: kind={artifact.get('kind')!r}"
            )
        rate = float(artifact["rate_evals_per_s"])
        if not (math.isfinite(rate) and rate > 0):
            raise ValueError(f"bad probed rate: {rate!r}")
        window = f"{float(artifact.get('probe_seconds', 0.0)):g}s"
        samples = int(artifact.get("samples", max(1, int(rate))))
        derived = derive_admission_thresholds(rate, table=self)
        for name, value in derived.items():
            self.set(name, value, source=SOURCE_PROBE, samples=samples,
                     window=window)
        with self._lock:
            self._probe = {
                "rate_evals_per_s": rate,
                "seed": artifact.get("seed"),
                "nodes": artifact.get("nodes"),
                "probe_seconds": artifact.get("probe_seconds"),
            }
        return len(derived)


def derive_admission_thresholds(
    rate_per_s: float, table: Optional[CalibrationTable] = None
) -> dict:
    """Backlog thresholds from a measured sustainable rate, via Little's
    law: a backlog of ``rate × T`` evals means an arriving eval already
    waits ``T`` seconds at the sustainable service rate — so enter
    brownout when the backlog implies the brownout p99 target is spent,
    and shed at the shed target. Floors keep tiny probe rates from
    collapsing the thresholds below useful hysteresis widths."""
    t = table if table is not None else global_table
    brownout_s = t.get("admission.brownout_p99_ms") / 1000.0
    shed_s = t.get("admission.shed_p99_ms") / 1000.0
    brownout_backlog = max(16.0, round(rate_per_s * brownout_s))
    shed_backlog = max(2.0 * brownout_backlog, round(rate_per_s * shed_s))
    # imbalance vote needs a real backlog behind it: an eighth of the
    # brownout point, floored where the shipped default floors
    imbalance_min = max(8.0, round(brownout_backlog / 8.0))
    return {
        "admission.brownout_backlog": float(brownout_backlog),
        "admission.shed_backlog": float(shed_backlog),
        "admission.imbalance_min_backlog": float(imbalance_min),
    }


def write_probe_artifact(
    path: str,
    rate_per_s: float,
    seed: int = 0,
    nodes: int = 0,
    probe_seconds: float = 0.0,
    samples: int = 0,
) -> dict:
    """Persist one ``saturation_search`` measurement as the canonical
    ``CALIB_r01.json`` shape (sorted keys — byte-reproducible for a
    given measurement)."""
    artifact = {
        "artifact": "CALIB_r01",
        "version": _PROBE_VERSION,
        "kind": _PROBE_KIND,
        "rate_evals_per_s": float(rate_per_s),
        "seed": int(seed),
        "nodes": int(nodes),
        "probe_seconds": float(probe_seconds),
        "samples": int(samples),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    return artifact


# -- online throughput estimation --------------------------------------------


class _Cell:
    __slots__ = ("ema", "samples", "hist", "updated_at_index", "updated_at")

    def __init__(self):
        self.ema = 0.0
        self.samples = 0
        self.hist = LogHistogram()
        self.updated_at_index = 0
        self.updated_at = 0.0


class ThroughputEstimator:
    """Online per-(device_class × job-profile) throughput estimates from
    the flight-recorder span stream.

    Input convention: any span whose tags carry ``device_class``,
    ``job_profile`` and ``work_units`` contributes one sample of
    ``work_units / duration_s`` to its cell. The EMA (seeded with the
    first sample so a constant stream converges exactly) is the point
    estimate; the per-cell :class:`LogHistogram` keeps the raw rate
    distribution for confidence/percentile reads.

    Reads go through :meth:`value`, which NEVER returns garbage: a cell
    below ``sample_floor`` answers with the caller's declared anchor
    (``source: default``), and a learned answer is clamped into
    ``[anchor/clamp_band, anchor×clamp_band]`` — invariant law 14
    (``calibration_sanity``) checks both properties.

    The chaos site ``calib.telemetry_drop`` drops input samples before
    they reach a cell, proving starvation degrades to declared.
    """

    def __init__(
        self,
        recorder=None,
        sample_floor: int = 8,
        clamp_band: float = 8.0,
        ema_alpha: float = 0.2,
        max_cells: int = 256,
        clock: Optional[Callable[[], float]] = None,
    ):
        if recorder is None:
            from .recorder import flight_recorder

            recorder = flight_recorder
        self._recorder = recorder
        self.sample_floor = int(sample_floor)
        self.clamp_band = float(clamp_band)
        self.ema_alpha = float(ema_alpha)
        self.max_cells = int(max_cells)
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        # bounded by construction: at most max_cells (class × profile)
        # entries; overflow drops the sample and bumps a counter
        self._cells: dict[tuple[str, str], _Cell] = {}
        self._index = 0
        self._attached = 0
        self._dropped = 0
        self._overflow = 0

    # -- recorder seam ----------------------------------------------------

    def attach(self) -> None:
        """Idempotent, refcounted subscribe to the recorder fan-out."""
        with self._lock:
            self._attached += 1
            if self._attached == 1:
                self._recorder.add_listener(self._on_trace)

    def detach(self) -> None:
        with self._lock:
            if self._attached == 0:
                return
            self._attached -= 1
            if self._attached == 0:
                self._recorder.remove_listener(self._on_trace)

    def _on_trace(self, trace: dict) -> None:
        for span in trace.get("spans") or ():
            tags = span.get("tags") or {}
            cls = tags.get("device_class")
            profile = tags.get("job_profile")
            work = tags.get("work_units")
            if cls is None or profile is None or work is None:
                continue
            dur_ms = span.get("duration_ms")
            if not dur_ms or dur_ms <= 0:
                continue
            self.observe(str(cls), str(profile),
                         float(work) / (float(dur_ms) / 1000.0))

    # -- writes -----------------------------------------------------------

    def observe(self, device_class: str, profile: str, rate: float) -> None:
        """One throughput sample (work units per second) for a cell."""
        if not (math.isfinite(rate) and rate > 0):
            return
        if chaos_site("calib.telemetry_drop") == "drop":
            with self._lock:
                self._dropped += 1
            global_metrics.incr("nomad.calib.telemetry_dropped")
            return
        key = (device_class, profile)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                if len(self._cells) >= self.max_cells:
                    self._overflow += 1
                    global_metrics.incr("nomad.calib.cell_overflow")
                    return
                cell = self._cells[key] = _Cell()
            self._index += 1
            if cell.samples == 0:
                cell.ema = rate
            else:
                cell.ema += self.ema_alpha * (rate - cell.ema)
            cell.samples += 1
            cell.hist.record(rate)
            cell.updated_at_index = self._index
            cell.updated_at = self._clock()
        global_metrics.incr("nomad.calib.samples")

    def reset(self) -> None:
        with self._lock:
            self._cells.clear()
            self._index = 0
            self._dropped = 0
            self._overflow = 0

    # -- reads ------------------------------------------------------------

    def value(
        self, device_class: str, profile: str, declared: float = 1.0
    ) -> tuple[float, str]:
        """(throughput, source) for a cell. Starved or unknown cells
        answer the declared anchor; learned answers are clamped into the
        band around it so one wild window can't distort placement by
        more than ``clamp_band``×."""
        declared = float(declared)
        with self._lock:
            cell = self._cells.get((device_class, profile))
            if cell is None or cell.samples < self.sample_floor:
                return declared, SOURCE_DEFAULT
            ema = cell.ema
        if not (math.isfinite(ema) and ema > 0):
            return declared, SOURCE_DEFAULT
        anchor = declared if declared > 0 else 1.0
        lo, hi = anchor / self.clamp_band, anchor * self.clamp_band
        return min(max(ema, lo), hi), SOURCE_LEARNED

    def confidence(self, device_class: str, profile: str) -> float:
        """0 at no samples, 0.5 at the floor, → 1 with volume."""
        with self._lock:
            cell = self._cells.get((device_class, profile))
            samples = cell.samples if cell is not None else 0
        return samples / (samples + float(self.sample_floor))

    def cell_count(self) -> int:
        with self._lock:
            return len(self._cells)

    def snapshot(self) -> dict:
        """The estimator matrix + provenance (``/v1/agent/calibration``,
        law 14, the SLO calibration block)."""
        with self._lock:
            cells = {}
            total = 0
            learned = 0
            for (cls, profile), cell in sorted(self._cells.items()):
                is_learned = cell.samples >= self.sample_floor
                learned += 1 if is_learned else 0
                total += cell.samples
                cells[f"{cls}|{profile}"] = {
                    "device_class": cls,
                    "profile": profile,
                    "ema": cell.ema,
                    "samples": cell.samples,
                    "confidence": cell.samples
                    / (cell.samples + float(self.sample_floor)),
                    "source": SOURCE_LEARNED if is_learned else SOURCE_DEFAULT,
                    "p50": cell.hist.percentile(0.50),
                    "updated_at_index": cell.updated_at_index,
                }
            return {
                "cells": cells,
                "cell_count": len(cells),
                "learned_cells": learned,
                "samples": total,
                "sample_floor": self.sample_floor,
                "clamp_band": self.clamp_band,
                "dropped": self._dropped,
                "overflow": self._overflow,
            }


def learned_tp_matrix(estimator, ct, asks, declared_tp: np.ndarray) -> np.ndarray:
    """Substitute learned per-class throughputs into a hetero batch's
    declared tp matrix (f32[G, N] in, f32[G, N] out — same shape/dtype,
    so the jitted kernel sees identical avals and nothing retraces).
    Only asks carrying a calibration ``profile`` are substituted; each
    cell falls back to its declared anchor below the sample floor."""
    ids, vocab = ct.device_class_column()
    ids = np.asarray(ids)
    out = np.array(declared_tp, dtype=np.float32, copy=True)
    first_row = {
        cid: int(w[0])
        for cid, w in (
            (cid, np.flatnonzero(ids == cid)) for cid in vocab.values()
        )
        if w.size
    }
    for i, a in enumerate(asks):
        profile = getattr(a, "profile", "") or ""
        if not profile:
            continue
        per_class = np.ones(len(vocab), dtype=np.float32)
        for name, cid in vocab.items():
            row = first_row.get(cid)
            anchor = float(declared_tp[i, row]) if row is not None else 1.0
            v, _src = estimator.value(name, profile, declared=anchor)
            per_class[cid] = np.float32(v)
        out[i] = per_class[ids]
    return out


# -- process-global instances -------------------------------------------------

global_table = CalibrationTable()
global_estimator = ThroughputEstimator()


def calibration_overview(table=None, estimator=None) -> dict:
    """The flat scalar block the SLO report embeds (schema-pinned)."""
    t = table if table is not None else global_table
    e = estimator if estimator is not None else global_estimator
    ts = t.snapshot()
    es = e.snapshot()
    return {
        "constants": len(ts["constants"]),
        "probe_sourced": ts["by_source"][SOURCE_PROBE],
        "learned_cells": es["learned_cells"],
        "estimator_samples": es["samples"],
    }


# -- the bench.py calib A/B gate ---------------------------------------------


def _profile_of(job_index: int) -> str:
    """The synthetic profile key for build_mixed_asks' three job kinds."""
    return f"kind{job_index % 3}"


def synth_execute_trace(
    trace_id: str, device_class: str, profile: str, work_units: float,
    duration_ms: float,
) -> dict:
    """A minimal flight-recorder trace carrying one estimator input
    span — the synthetic telemetry shape tests and the calib bench feed
    through the REAL listener fan-out."""
    return {
        "trace_id": trace_id,
        "eval_id": trace_id,
        "status": "ok",
        "started_at": 0.0,
        "duration_ms": duration_ms,
        "tags": {"priority": 50},
        "spans": [
            {
                "span_id": f"{trace_id}-s0",
                "parent_id": None,
                "name": "execute",
                "start_unix": 0.0,
                "duration_ms": duration_ms,
                "status": "ok",
                "tags": {
                    "device_class": device_class,
                    "job_profile": profile,
                    "work_units": work_units,
                },
            }
        ],
    }


def _blind_asks(asks) -> list:
    """Strip declared coefficients, keep only the profile key — what the
    policies see in learned mode (declared hidden from them)."""
    import copy

    out = []
    for j, a in enumerate(asks):
        b = copy.copy(a)
        b.throughputs = None
        b.has_throughputs = False
        b.profile = _profile_of(j)
        out.append(b)
    return out


def run_calib_ab(
    n_nodes: int = 1000,
    n_jobs: int = 12,
    count_per_job: int = 25,
    seed: int = 42,
    samples_per_cell: int = 24,
    tolerance: float = 0.25,
) -> dict:
    """The ``bench.py calib`` block: the PR-9 hetero A/B rerun with
    throughputs learned ONLINE from span telemetry.

    Declared coefficients are hidden from the policies (asks carry only
    a profile key); the estimator learns each (class × profile) cell
    from synthetic execute spans fed through a real FlightRecorder
    fan-out whose per-sample rates carry deterministic jitter around the
    true coefficient. Gate: the learned run must reproduce the hetero
    wins (maxmin worst-share lift, makespan reduction) within
    ``tolerance`` of the declared run, the declared mode must stay
    byte-identical with the estimator in the room, and the hetero kernel
    must not retrace."""
    from ..analysis import retrace
    from ..device.score import PlacementKernel
    from ..scheduler.hetero import (
        HeteroPlacementKernel,
        _quality_metrics,
        build_mixed_asks,
        build_mixed_fleet,
        run_hetero_ab,
    )
    from .recorder import FlightRecorder

    declared_report = run_hetero_ab(n_nodes, n_jobs, count_per_job, seed)

    ct = build_mixed_fleet(n_nodes, seed=seed)
    asks = build_mixed_asks(ct, n_jobs, count_per_job, seed=seed + 1)
    ids_arr, vocab = ct.device_class_column()
    ids_arr = np.asarray(ids_arr)
    class_names = sorted(k for k in vocab if k)

    # ground truth straight from the declared vectors about to be hidden:
    # the per-class coefficient of each job kind is what the synthetic
    # telemetry encodes and the estimator must recover
    maps = []
    for kind in range(min(3, n_jobs)):
        m = {}
        for name, cid in vocab.items():
            if not name:
                continue
            rows = np.flatnonzero(ids_arr == cid)
            if rows.size and asks[kind].throughputs is not None:
                m[name] = float(asks[kind].throughputs[rows[0]])
        maps.append(m)

    # learn online: dedicated recorder so the stream is exactly the
    # synthetic telemetry, fed through the production fan-out seam
    recorder = FlightRecorder()
    estimator = ThroughputEstimator(recorder=recorder, clock=lambda: 0.0)
    estimator.attach()
    n_traces = 0
    for kind, m in enumerate(maps):
        profile = f"kind{kind}"
        for cls in class_names:
            coeff = m.get(cls, 1.0)
            for k in range(samples_per_cell):
                # ±10% deterministic jitter: the estimator sees noisy
                # rates, never the coefficient itself
                jitter = 1.0 + 0.1 * math.sin(float(2 * k + kind))
                recorder.record(
                    synth_execute_trace(
                        f"calib-{profile}-{cls}-{k}", cls, profile,
                        work_units=coeff * jitter, duration_ms=1000.0,
                    )
                )
                n_traces += 1
    estimator.detach()

    blind = _blind_asks(asks)
    retrace_before = dict(retrace.counts())

    base = PlacementKernel("binpack")
    base_results = base.place(ct, asks)
    report: dict = {
        "config": {
            "nodes": n_nodes,
            "jobs": n_jobs,
            "count_per_job": count_per_job,
            "seed": seed,
            "samples_per_cell": samples_per_cell,
            "tolerance": tolerance,
            "traces_fed": n_traces,
            "device_classes": class_names,
        },
        "estimator": estimator.snapshot(),
        "binpack": _quality_metrics(ct, asks, base_results),
        "policies": {},
    }

    declared_identical = True
    for policy in ("maxmin", "makespan", "cost"):
        learned_kern = HeteroPlacementKernel(
            policy, throughput_source="learned", estimator=estimator
        )
        learned_results = learned_kern.place(ct, blind)
        # score quality against the TRUE declared coefficients — the
        # policies never saw them, so recovered wins are real
        metrics = _quality_metrics(ct, asks, learned_results)
        report["policies"][f"hetero-{policy}"] = metrics

        # declared-mode pin: same kernel class, estimator in the room,
        # throughput_source=declared — placements must be byte-identical
        # to a pre-calibration kernel's
        plain = HeteroPlacementKernel(policy).place(ct, asks)
        pinned = HeteroPlacementKernel(
            policy, throughput_source="declared", estimator=estimator
        ).place(ct, asks)
        for r0, r1 in zip(plain, pinned):
            if (
                r0.node_rows.tobytes() != r1.node_rows.tobytes()
                or r0.scores.tobytes() != r1.scores.tobytes()
            ):
                declared_identical = False

    retrace_after = dict(retrace.counts())
    added_retraces = sum(
        retrace_after.get(k, 0) - retrace_before.get(k, 0)
        for k in retrace_after
    )

    b = report["binpack"]
    mm = report["policies"]["hetero-maxmin"]
    ms = report["policies"]["hetero-makespan"]
    learned_ab = {
        "maxmin_worst_share_delta": round(mm["worst_share"] - b["worst_share"], 4),
        "makespan_delta": round(b["makespan"] - ms["makespan"], 4),
        "maxmin_improves_worst_share": mm["worst_share"] > b["worst_share"],
        "makespan_reduced": ms["makespan"] < b["makespan"],
    }
    declared_ab = declared_report["ab"]

    def _within(learned: float, declared: float) -> bool:
        return abs(learned - declared) <= tolerance * max(abs(declared), 1e-9)

    report["ab"] = {
        "declared": declared_ab,
        "learned": learned_ab,
        "worst_share_within_tolerance": _within(
            learned_ab["maxmin_worst_share_delta"],
            declared_ab["maxmin_worst_share_delta"],
        ),
        "makespan_within_tolerance": _within(
            learned_ab["makespan_delta"], declared_ab["makespan_delta"]
        ),
    }
    report["declared_mode_identical"] = declared_identical
    report["added_retraces"] = added_retraces
    report["ok"] = (
        declared_report["ok"]
        and learned_ab["maxmin_improves_worst_share"]
        and learned_ab["makespan_reduced"]
        and report["ab"]["worst_share_within_tolerance"]
        and report["ab"]["makespan_within_tolerance"]
        and declared_identical
        and added_retraces == 0
    )
    return report
