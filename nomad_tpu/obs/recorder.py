"""Flight recorder — fixed-size ring of recent completed traces.

The analog of an aircraft FDR for the scheduler: the last N eval traces
and the last N error events stay resident, cheap enough to leave on in
production, and are surfaced at ``/v1/agent/trace`` next to
``/v1/metrics``. ``render_trace`` turns one recorded tree into the
indented duration view the ``nomad-tpu trace`` CLI prints;
``phase_breakdown`` aggregates span durations by name for the BENCH
per-phase report.

Zero dependencies beyond the stdlib; traces arrive as plain dicts (see
``Tracer.finish``) so the recorder never holds live Span objects.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Optional

from ..utils.hist import pct_nearest_rank
from ..utils.metrics import global_metrics

DEFAULT_CAPACITY = 256
DEFAULT_ERROR_CAPACITY = 100


class FlightRecorder:
    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        error_capacity: int = DEFAULT_ERROR_CAPACITY,
        clock=None,
    ):
        self.capacity = capacity
        # injectable wall clock for error-event stamps (NTA008)
        self._clock = clock if clock is not None else time.time
        self._lock = threading.Lock()
        # eval_id → trace dict, insertion-ordered: oldest first, evicted
        # first; a re-processed eval re-records and moves to the tail
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self._errors: deque = deque(maxlen=error_capacity)
        # lifetime error-event count: the ring evicts, this doesn't, so
        # conservation checks (chaos invariant: every swallowed-error
        # counter bump has a ring event) survive ring wraparound
        self.errors_total = 0
        # lifetime trace counts: how much of a run the 256-trace ring
        # actually covered, so SLO reports can state coverage instead
        # of silently truncating to the newest 256
        self.traces_total = 0
        self.traces_evicted = 0
        # listeners see every completed trace even when the ring
        # wraps — the SLO collector windows latencies through this
        self._listeners: list[Callable[[dict], None]] = []
        # placement-explanation ring (obs/explain.py): eval_id → payload
        # dict, same capacity/eviction discipline as the trace ring so
        # `alloc why` / `/v1/evaluations/:id/placement` have a bounded,
        # always-on store; lifetime counters state coverage like traces
        self._explanations: "OrderedDict[str, dict]" = OrderedDict()
        self.explanations_total = 0
        self.explanations_evicted = 0

    # -- writes ------------------------------------------------------------
    def add_listener(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def record(self, trace: dict) -> None:
        eval_id = trace.get("eval_id", "")
        evicted = 0
        with self._lock:
            if eval_id in self._traces:
                del self._traces[eval_id]
            self._traces[eval_id] = trace
            self.traces_total += 1
            while len(self._traces) > self.capacity:
                self._traces.popitem(last=False)
                evicted += 1
            self.traces_evicted += evicted
            listeners = list(self._listeners)
        # metrics bump + listener fan-out happen OUTSIDE the recorder
        # lock: listeners take their own locks, and the registry lock
        # must never nest under this one (same rule as Tracer.finish)
        if evicted:
            global_metrics.incr("nomad.obs.traces_evicted", evicted)
        eval_s, placement_s = trace_latencies(trace)
        global_metrics.measure("nomad.slo.eval_latency", eval_s)
        # high-priority tier gets its own always-on series: the
        # admission plane promises this one stays within SLO while
        # lower tiers are deferred/shed, so it must be observable
        # lifetime (live_report) not just per-collector
        priority = (trace.get("tags") or {}).get("priority")
        if priority is not None:
            from ..server.admission import TIER_HIGH, tier_of

            if tier_of(int(priority)) == TIER_HIGH:
                global_metrics.measure("nomad.slo.eval_latency_high", eval_s)
        if placement_s > 0.0:
            global_metrics.measure("nomad.slo.placement_latency", placement_s)
        for fn in listeners:
            try:
                fn(trace)
            except Exception:
                global_metrics.incr("nomad.obs.listener_errors")

    def record_explanation(self, eval_id: str, payload: dict) -> None:
        """Ring one eval's placement explanation (dict of task group →
        explanation dict, plus eval metadata). Re-records move to the
        tail; evictions bump ``nomad.obs.explanations_evicted`` outside
        the lock, mirroring ``record``."""
        evicted = 0
        with self._lock:
            if eval_id in self._explanations:
                del self._explanations[eval_id]
            self._explanations[eval_id] = payload
            self.explanations_total += 1
            while len(self._explanations) > self.capacity:
                self._explanations.popitem(last=False)
                evicted += 1
            self.explanations_evicted += evicted
        if evicted:
            global_metrics.incr("nomad.obs.explanations_evicted", evicted)
        global_metrics.incr("nomad.obs.explanations_recorded")

    def explanation(self, eval_id: str) -> Optional[dict]:
        with self._lock:
            return self._explanations.get(eval_id)

    def explanations(self, n: int = 50) -> list[dict]:
        """Newest-first explanation payloads (bounded index view)."""
        with self._lock:
            items = list(reversed(self._explanations.values()))
        return items[: max(0, n)]

    def record_error(
        self, component: str, error: str, eval_id: str = ""
    ) -> None:
        with self._lock:
            self.errors_total += 1
            self._errors.append(
                {
                    "at_unix": self._clock(),
                    "component": component,
                    "error": error,
                    "eval_id": eval_id,
                }
            )

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._errors.clear()
            self._explanations.clear()

    # -- reads -------------------------------------------------------------
    def get(self, eval_id: str) -> Optional[dict]:
        with self._lock:
            return self._traces.get(eval_id)

    def traces(self) -> list[dict]:
        """Full trace dicts, newest first."""
        with self._lock:
            return list(reversed(self._traces.values()))

    def list(self, n: int = 50) -> list[dict]:
        """Newest-first summaries (the trace index endpoint)."""
        out = []
        for t in self.traces()[: max(0, n)]:
            out.append(
                {
                    "eval_id": t.get("eval_id", ""),
                    "status": t.get("status", ""),
                    "started_at": t.get("started_at", 0.0),
                    "duration_ms": t.get("duration_ms", 0.0),
                    "spans": len(t.get("spans", ())),
                    "tags": t.get("tags", {}),
                }
            )
        return out

    def errors(self) -> list[dict]:
        with self._lock:
            return list(reversed(self._errors))

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


def trace_latencies(trace: dict) -> tuple[float, float]:
    """(eval_latency_s, placement_latency_s) for one completed trace —
    THE latency definitions every SLO surface shares.

    Eval latency is end-to-end from the user's side of the broker:
    ready-queue wait (the ``queue_wait_ms`` tag the worker stamps on
    the dequeue span) plus the trace's own dequeue→ack duration.
    Placement latency is the schedule-and-commit core: the summed
    durations of the ``invoke_scheduler`` and ``submit_plan`` spans.
    """
    queue_wait_ms = 0.0
    placement_ms = 0.0
    for s in trace.get("spans", ()):
        name = s.get("name", "")
        if name == "dequeue":
            try:
                queue_wait_ms += float(
                    s.get("tags", {}).get("queue_wait_ms", 0.0)
                )
            except (TypeError, ValueError):
                pass
        elif name in ("invoke_scheduler", "submit_plan"):
            placement_ms += float(s.get("duration_ms") or 0.0)
    eval_ms = queue_wait_ms + float(trace.get("duration_ms") or 0.0)
    return eval_ms / 1000.0, placement_ms / 1000.0


flight_recorder = FlightRecorder()


def render_trace(trace: dict) -> str:
    """Render one recorded trace as an indented duration tree::

        eval 4bb1…  acked  12.41ms  job_id=bench-3
          dequeue              0.31ms  queue_wait_ms=0.21
          wait_for_index       0.02ms
          ...
    """
    spans = trace.get("spans", [])
    children: dict = {}
    roots = []
    for s in spans:
        pid = s.get("parent_id")
        if pid is None:
            roots.append(s)
        else:
            children.setdefault(pid, []).append(s)

    def fmt_tags(tags: dict) -> str:
        return " ".join(f"{k}={v}" for k, v in sorted(tags.items()))

    header_tags = fmt_tags(trace.get("tags", {}))
    lines = [
        f"eval {trace.get('eval_id', '?')}  {trace.get('status', '?')}  "
        f"{trace.get('duration_ms', 0.0):.2f}ms"
        + (f"  {header_tags}" if header_tags else "")
    ]

    def walk(span: dict, depth: int) -> None:
        tags = fmt_tags(span.get("tags", {}))
        name = "  " * depth + span["name"]
        lines.append(
            f"{name:<40s} {span.get('duration_ms', 0.0):>10.2f}ms"
            + (f"  {tags}" if tags else "")
        )
        kids = children.get(span.get("span_id"), [])
        for kid in sorted(kids, key=lambda s: s.get("start_unix", 0.0)):
            walk(kid, depth + 1)

    for root in roots:
        for kid in sorted(
            children.get(root.get("span_id"), []),
            key=lambda s: s.get("start_unix", 0.0),
        ):
            walk(kid, 1)
    return "\n".join(lines)


def phase_breakdown(traces: list[dict]) -> dict:
    """Aggregate span durations by name across traces — the BENCH
    per-phase latency table. Root spans are excluded (the root is the
    whole eval; the phases are its children)."""
    by_name: dict[str, list[float]] = {}
    for t in traces:
        for s in t.get("spans", ()):
            if s.get("parent_id") is None:
                continue
            by_name.setdefault(s["name"], []).append(
                float(s.get("duration_ms") or 0.0)
            )
    out = {}
    for name in sorted(by_name):
        buf = sorted(by_name[name])
        n = len(buf)
        p95 = pct_nearest_rank(buf, 0.95)
        out[name] = {
            "count": n,
            "mean_ms": round(sum(buf) / n, 3),
            "p95_ms": round(p95, 3),
            "max_ms": round(buf[-1], 3),
        }
    return out
