"""HCL jobspec parser.

Reference grammar: jobspec/parse.go + jobspec2/parse_job.go — the
``job`` block with nested group/task/resources/constraint/affinity/
spread/update/periodic/parameterized/network/restart/reschedule/
migrate/ephemeral_disk/lifecycle/artifact/template/meta stanzas —
plus jobspec2's two-phase evaluation: ``variable``/``locals`` blocks
are collected first, then the job body is evaluated with ``var.*`` /
``local.*`` in scope (jobspec2/parse.go:19, jobspec2/types.variables.go).

Durations accept Go syntax ("30s", "5m", "1h30m", "500ms").
"""

from __future__ import annotations

import re
from typing import Any, Optional

from ..structs.job import (
    Affinity,
    Constraint,
    CONSTRAINT_ATTRIBUTE_IS_NOT_SET,
    CONSTRAINT_ATTRIBUTE_IS_SET,
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
    CONSTRAINT_REGEX,
    CONSTRAINT_SEMVER,
    CONSTRAINT_SET_CONTAINS,
    CONSTRAINT_VERSION,
    EphemeralDisk,
    Job,
    JOB_DEFAULT_PRIORITY,
    MigrateStrategy,
    ParameterizedJobConfig,
    PeriodicConfig,
    ReschedulePolicy,
    RestartPolicy,
    Spread,
    SpreadTarget,
    Task,
    TaskGroup,
    UpdateStrategy,
)
from ..structs.resources import NetworkResource, RequestedDevice, Resources
from ..utils import hcl


class JobspecError(Exception):
    pass


class _RuntimeRef:
    """Self-quoting placeholder for scheduler-time interpolation targets.

    ``${attr.kernel.name}`` / ``${node.datacenter}`` / ``${meta.rack}`` are
    NOT jobspec variables — the scheduler resolves them per node
    (scheduler/feasible.go:748-781 resolveTarget). Evaluating one here
    reproduces the literal ``${...}`` text so it survives into the
    Constraint/Affinity/Spread structs unchanged.
    """

    def __init__(self, path: str):
        self._path = path

    def __getattr__(self, key: str) -> "_RuntimeRef":
        if key.startswith("_"):
            raise AttributeError(key)
        return _RuntimeRef(f"{self._path}.{key}")

    def __getitem__(self, key) -> "_RuntimeRef":
        return _RuntimeRef(f"{self._path}.{key}")

    def __str__(self) -> str:
        return "${" + self._path + "}"


# env.* and NOMAD_* also interpolate at task runtime (client/taskenv)
RUNTIME_VARS = ("attr", "node", "meta", "device", "env")


def _jobspec_ctx(variables: dict, local_values: dict) -> hcl.EvalContext:
    scope: dict[str, Any] = {name: _RuntimeRef(name) for name in RUNTIME_VARS}
    scope["var"] = variables
    scope["local"] = local_values
    return hcl.EvalContext(scope)


_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h|d)")
_DURATION_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
    "d": 86400.0,
}


def parse_duration(v: Any) -> float:
    """Go-style duration → seconds. Numbers pass through as seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    if not s:
        return 0.0
    pos = 0
    total = 0.0
    for m in _DURATION_RE.finditer(s):
        if m.start() != pos:
            raise JobspecError(f"invalid duration {v!r}")
        total += float(m.group(1)) * _DURATION_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise JobspecError(f"invalid duration {v!r}")
    return total


def _attrs(body: hcl.Body, ctx: hcl.EvalContext) -> dict[str, Any]:
    return {name: a.expr(ctx) for name, a in body.attrs.items()}


def _meta(body: hcl.Body, ctx: hcl.EvalContext) -> dict[str, str]:
    """meta {} block or meta = {} attribute."""
    out: dict[str, str] = {}
    for b in body.blocks_of("meta"):
        out.update({k: str(v) for k, v in _attrs(b.body, ctx).items()})
    if "meta" in body.attrs:
        out.update(
            {k: str(v) for k, v in (body.attrs["meta"].expr(ctx) or {}).items()}
        )
    return out


# -- constraint / affinity / spread -----------------------------------------

_CONSTRAINT_SHORTHANDS = {
    # attr name in the block → operand it implies (jobspec/parse.go
    # parseConstraints: regexp/version/semver/distinct_hosts/...)
    "regexp": CONSTRAINT_REGEX,
    "version": CONSTRAINT_VERSION,
    "semver": CONSTRAINT_SEMVER,
    "set_contains": CONSTRAINT_SET_CONTAINS,
    "set_contains_any": "set_contains_any",
    "set_contains_all": "set_contains_all",
}


def _parse_constraint(b: hcl.Body, ctx: hcl.EvalContext) -> Constraint:
    a = _attrs(b, ctx)
    c = Constraint(
        l_target=str(a.get("attribute", "")),
        operand=str(a.get("operator", "=")),
        r_target=str(a.get("value", "")),
    )
    for short, operand in _CONSTRAINT_SHORTHANDS.items():
        if short in a:
            c.operand = operand
            c.r_target = str(a[short])
    if a.get("distinct_hosts"):
        c.operand = CONSTRAINT_DISTINCT_HOSTS
        c.l_target = c.r_target = ""
    if "distinct_property" in a:
        c.operand = CONSTRAINT_DISTINCT_PROPERTY
        c.l_target = str(a["distinct_property"])
        c.r_target = str(a.get("value", "")) if "value" in a else ""
    if c.operand in (CONSTRAINT_ATTRIBUTE_IS_SET, CONSTRAINT_ATTRIBUTE_IS_NOT_SET):
        c.r_target = ""
    return c


def _parse_affinity(b: hcl.Body, ctx: hcl.EvalContext) -> Affinity:
    a = _attrs(b, ctx)
    aff = Affinity(
        l_target=str(a.get("attribute", "")),
        operand=str(a.get("operator", "=")),
        r_target=str(a.get("value", "")),
        weight=int(a.get("weight", 50)),
    )
    for short, operand in _CONSTRAINT_SHORTHANDS.items():
        if short in a:
            aff.operand = operand
            aff.r_target = str(a[short])
    return aff


def _parse_spread(b: hcl.Body, ctx: hcl.EvalContext) -> Spread:
    a = _attrs(b, ctx)
    sp = Spread(
        attribute=str(a.get("attribute", "")), weight=int(a.get("weight", 50))
    )
    for tb in b.blocks_of("target"):
        ta = _attrs(tb.body, ctx)
        label = tb.labels[0] if tb.labels else str(ta.get("value", ""))
        sp.targets.append(
            SpreadTarget(value=label, percent=int(ta.get("percent", 0)))
        )
    return sp


def _collect_cas(body: hcl.Body, ctx, constraints, affinities, spreads=None):
    for cb in body.blocks_of("constraint"):
        constraints.append(_parse_constraint(cb.body, ctx))
    for ab in body.blocks_of("affinity"):
        affinities.append(_parse_affinity(ab.body, ctx))
    if spreads is not None:
        for sb in body.blocks_of("spread"):
            spreads.append(_parse_spread(sb.body, ctx))


# -- resources ---------------------------------------------------------------


def _parse_network(b: hcl.Body, ctx: hcl.EvalContext) -> NetworkResource:
    a = _attrs(b, ctx)
    net = NetworkResource(
        mode=str(a.get("mode", "host")), mbits=int(a.get("mbits", 0))
    )
    for pb in b.blocks_of("port"):
        label = pb.labels[0] if pb.labels else ""
        pa = _attrs(pb.body, ctx)
        if "static" in pa:
            net.reserved_ports.append(int(pa["static"]))
        else:
            net.dynamic_ports.append(label)
    return net


def _parse_resources(b: hcl.Body, ctx: hcl.EvalContext) -> Resources:
    a = _attrs(b, ctx)
    res = Resources(
        cpu=int(a.get("cpu", 100)),
        memory_mb=int(a.get("memory", a.get("memory_mb", 300))),
        disk_mb=int(a.get("disk", a.get("disk_mb", 0))),
    )
    for nb in b.blocks_of("network"):
        res.networks.append(_parse_network(nb.body, ctx))
    for db in b.blocks_of("device"):
        name = db.labels[0] if db.labels else ""
        da = _attrs(db.body, ctx)
        dev = RequestedDevice(name=name, count=int(da.get("count", 1)))
        _collect_cas(db.body, ctx, dev.constraints, dev.affinities)
        res.devices.append(dev)
    return res


# -- task ---------------------------------------------------------------------


def _parse_task(block: hcl.Block, ctx: hcl.EvalContext) -> Task:
    if not block.labels:
        raise JobspecError("task block requires a name label")
    b = block.body
    a = _attrs(b, ctx)
    t = Task(
        name=block.labels[0],
        driver=str(a.get("driver", "exec")),
        user=str(a.get("user", "")),
        leader=bool(a.get("leader", False)),
        kind=str(a.get("kind", "")),
    )
    if "kill_timeout" in a:
        t.kill_timeout_s = parse_duration(a["kill_timeout"])
    cfg = b.first("config")
    if cfg is not None:
        t.config = _attrs(cfg.body, ctx)
    env = b.first("env")
    if env is not None:
        t.env = {k: str(v) for k, v in _attrs(env.body, ctx).items()}
    res = b.first("resources")
    if res is not None:
        t.resources = _parse_resources(res.body, ctx)
    lc = b.first("lifecycle")
    if lc is not None:
        la = _attrs(lc.body, ctx)
        t.lifecycle_hook = str(la.get("hook", ""))
        t.lifecycle_sidecar = bool(la.get("sidecar", False))
    logs = b.first("logs")
    if logs is not None:
        from ..structs.job import LogConfig

        lga = _attrs(logs.body, ctx)
        t.log_config = LogConfig(
            max_files=int(lga.get("max_files", 10)),
            max_file_size_mb=int(lga.get("max_file_size", 10)),
        )
    for vm in b.blocks_of("volume_mount"):
        from ..structs.volumes import VolumeMount

        va = _attrs(vm.body, ctx)
        t.volume_mounts.append(
            VolumeMount(
                volume=str(va.get("volume", "")),
                destination=str(va.get("destination", "")),
                read_only=bool(va.get("read_only", False)),
            )
        )
    for ab in b.blocks_of("artifact"):
        t.artifacts.append(_attrs(ab.body, ctx))
    for tb in b.blocks_of("template"):
        t.templates.append(_attrs(tb.body, ctx))
    t.meta = _meta(b, ctx)
    _collect_cas(b, ctx, t.constraints, t.affinities)
    return t


# -- group ---------------------------------------------------------------------


def _parse_restart(b: hcl.Body, ctx) -> RestartPolicy:
    a = _attrs(b, ctx)
    rp = RestartPolicy()
    if "attempts" in a:
        rp.attempts = int(a["attempts"])
    if "interval" in a:
        rp.interval_s = parse_duration(a["interval"])
    if "delay" in a:
        rp.delay_s = parse_duration(a["delay"])
    if "mode" in a:
        rp.mode = str(a["mode"])
    return rp


def _parse_reschedule(b: hcl.Body, ctx) -> ReschedulePolicy:
    a = _attrs(b, ctx)
    rp = ReschedulePolicy()
    if "attempts" in a:
        rp.attempts = int(a["attempts"])
        rp.unlimited = False
    if "interval" in a:
        rp.interval_s = parse_duration(a["interval"])
    if "delay" in a:
        rp.delay_s = parse_duration(a["delay"])
    if "delay_function" in a:
        rp.delay_function = str(a["delay_function"])
    if "max_delay" in a:
        rp.max_delay_s = parse_duration(a["max_delay"])
    if "unlimited" in a:
        rp.unlimited = bool(a["unlimited"])
    return rp


def _parse_update(b: hcl.Body, ctx) -> UpdateStrategy:
    a = _attrs(b, ctx)
    u = UpdateStrategy()
    if "max_parallel" in a:
        u.max_parallel = int(a["max_parallel"])
    if "health_check" in a:
        u.health_check = str(a["health_check"])
    if "min_healthy_time" in a:
        u.min_healthy_time_s = parse_duration(a["min_healthy_time"])
    if "healthy_deadline" in a:
        u.healthy_deadline_s = parse_duration(a["healthy_deadline"])
    if "progress_deadline" in a:
        u.progress_deadline_s = parse_duration(a["progress_deadline"])
    if "auto_revert" in a:
        u.auto_revert = bool(a["auto_revert"])
    if "auto_promote" in a:
        u.auto_promote = bool(a["auto_promote"])
    if "canary" in a:
        u.canary = int(a["canary"])
    if "stagger" in a:
        u.stagger_s = parse_duration(a["stagger"])
    return u


def _parse_migrate(b: hcl.Body, ctx) -> MigrateStrategy:
    a = _attrs(b, ctx)
    m = MigrateStrategy()
    if "max_parallel" in a:
        m.max_parallel = int(a["max_parallel"])
    if "health_check" in a:
        m.health_check = str(a["health_check"])
    if "min_healthy_time" in a:
        m.min_healthy_time_s = parse_duration(a["min_healthy_time"])
    if "healthy_deadline" in a:
        m.healthy_deadline_s = parse_duration(a["healthy_deadline"])
    return m


def _parse_group(block: hcl.Block, ctx: hcl.EvalContext, job: Job) -> TaskGroup:
    if not block.labels:
        raise JobspecError("group block requires a name label")
    b = block.body
    a = _attrs(b, ctx)
    tg = TaskGroup(name=block.labels[0], count=int(a.get("count", 1)))
    if "stop_after_client_disconnect" in a:
        tg.stop_after_client_disconnect_s = parse_duration(
            a["stop_after_client_disconnect"]
        )
    rb = b.first("restart")
    if rb is not None:
        tg.restart_policy = _parse_restart(rb.body, ctx)
    rs = b.first("reschedule")
    if rs is not None:
        tg.reschedule_policy = _parse_reschedule(rs.body, ctx)
    ub = b.first("update")
    if ub is not None:
        tg.update = _parse_update(ub.body, ctx)
    mb = b.first("migrate")
    if mb is not None:
        tg.migrate = _parse_migrate(mb.body, ctx)
    eb = b.first("ephemeral_disk")
    if eb is not None:
        ea = _attrs(eb.body, ctx)
        tg.ephemeral_disk = EphemeralDisk(
            size_mb=int(ea.get("size", 300)),
            sticky=bool(ea.get("sticky", False)),
            migrate=bool(ea.get("migrate", False)),
        )
    for vb in b.blocks_of("volume"):
        from ..structs.volumes import VolumeRequest

        if not vb.labels:
            raise JobspecError("volume block requires a name label")
        va = _attrs(vb.body, ctx)
        tg.volumes[vb.labels[0]] = VolumeRequest(
            name=vb.labels[0],
            type=str(va.get("type", "host")),
            source=str(va.get("source", "")),
            read_only=bool(va.get("read_only", False)),
            per_alloc=bool(va.get("per_alloc", False)),
            access_mode=str(va.get("access_mode", "")),
            attachment_mode=str(va.get("attachment_mode", "")),
        )
    for nb in b.blocks_of("network"):
        tg.networks.append(_parse_network(nb.body, ctx))
    sb = b.first("scaling")
    if sb is not None:
        from ..structs.job import ScalingPolicy

        sa = _attrs(sb.body, ctx)
        pol = {}
        pb = sb.body.first("policy")
        if pb is not None:
            pol = _attrs(pb.body, ctx)
        tg.scaling = ScalingPolicy(
            min=int(sa.get("min", 0)),
            max=int(sa.get("max", 0)),
            enabled=bool(sa.get("enabled", True)),
            policy=pol,
        )
    _collect_cas(b, ctx, tg.constraints, tg.affinities, tg.spreads)
    tg.meta = _meta(b, ctx)
    for tb in b.blocks_of("task"):
        tg.tasks.append(_parse_task(tb, ctx))
    if not tg.tasks:
        raise JobspecError(f"group {tg.name!r} has no tasks")
    return tg


# -- job ------------------------------------------------------------------------


def _parse_throughputs(b: hcl.Body, ctx: hcl.EvalContext, job_id: str) -> dict:
    """``throughput {}`` block or ``throughput = {...}`` attribute:
    device_class → relative rate coefficient. Rejected with a structured
    JobspecError (one line per offending coefficient) instead of letting
    NaN/negative/garbage values propagate into the scoring kernels."""
    from ..structs.job import validate_throughputs

    raw: dict[str, Any] = {}
    for tb in b.blocks_of("throughput"):
        raw.update(_attrs(tb.body, ctx))
    if "throughput" in b.attrs:
        val = b.attrs["throughput"].expr(ctx)
        if not isinstance(val, dict):
            raise JobspecError(
                f"job {job_id!r}: throughput must be a mapping of "
                f"device_class -> coefficient, got {type(val).__name__}"
            )
        raw.update(val)
    if not raw:
        return {}
    problems = validate_throughputs(raw)
    if problems:
        raise JobspecError(
            f"job {job_id!r}: invalid throughput stanza:\n  "
            + "\n  ".join(problems)
        )
    return {k: float(v) for k, v in raw.items()}


def _parse_gang(b: hcl.Body, ctx: hcl.EvalContext, job_id: str) -> dict:
    """``gang {}`` block: all-or-nothing member groups plus optional
    colocate/spread topology terms. Group-name references are checked
    against the job's real groups later (validate_job), after groups
    have parsed; the structural checks reject here with exact
    messages."""
    from ..structs.job import validate_gang

    gb = b.first("gang")
    if gb is None:
        return {}
    ga = _attrs(gb.body, ctx)
    gang: dict[str, Any] = {}
    if "groups" in ga:
        gang["groups"] = list(ga["groups"]) if isinstance(
            ga["groups"], (list, tuple)
        ) else ga["groups"]
    for stanza in ("colocate", "spread"):
        tb = gb.body.first(stanza)
        if tb is not None:
            gang[stanza] = _attrs(tb.body, ctx)
        elif stanza in ga:
            gang[stanza] = ga[stanza]
    problems = validate_gang(gang)
    if problems:
        raise JobspecError(
            f"job {job_id!r}: invalid gang stanza:\n  " + "\n  ".join(problems)
        )
    return gang


def parse_job(block: hcl.Block, ctx: hcl.EvalContext) -> Job:
    if not block.labels:
        raise JobspecError("job block requires an id label")
    b = block.body
    a = _attrs(b, ctx)
    job = Job(
        id=block.labels[0],
        name=str(a.get("name", block.labels[0])),
        namespace=str(a.get("namespace", "default")),
        type=str(a.get("type", "service")),
        priority=int(a.get("priority", JOB_DEFAULT_PRIORITY)),
        region=str(a.get("region", "global")),
        all_at_once=bool(a.get("all_at_once", False)),
    )
    if "datacenters" in a:
        job.datacenters = [str(d) for d in a["datacenters"]]
    pb = b.first("periodic")
    if pb is not None:
        pa = _attrs(pb.body, ctx)
        job.periodic = PeriodicConfig(
            enabled=bool(pa.get("enabled", True)),
            spec=str(pa.get("cron", pa.get("spec", ""))),
            prohibit_overlap=bool(pa.get("prohibit_overlap", False)),
            time_zone=str(pa.get("time_zone", "UTC")),
        )
    qb = b.first("parameterized")
    if qb is not None:
        qa = _attrs(qb.body, ctx)
        job.parameterized = ParameterizedJobConfig(
            payload=str(qa.get("payload", "optional")),
            meta_required=[str(x) for x in qa.get("meta_required", [])],
            meta_optional=[str(x) for x in qa.get("meta_optional", [])],
        )
    _collect_cas(b, ctx, job.constraints, job.affinities, job.spreads)
    job.meta = _meta(b, ctx)
    job.throughputs = _parse_throughputs(b, ctx, job.id)
    job.gang = _parse_gang(b, ctx, job.id)
    # job-level update{} is the default for all groups (jobspec semantics)
    job_update: Optional[UpdateStrategy] = None
    ub = b.first("update")
    if ub is not None:
        job_update = _parse_update(ub.body, ctx)
    for gb in b.blocks_of("group"):
        tg = _parse_group(gb, ctx, job)
        if tg.update is None and job_update is not None:
            import copy

            tg.update = copy.copy(job_update)
        job.task_groups.append(tg)
    if not job.task_groups:
        raise JobspecError(f"job {job.id!r} has no groups")
    if job.type not in ("service", "batch", "system", "sysbatch"):
        raise JobspecError(f"invalid job type {job.type!r}")
    return job


def parse_job_file(src: str, variables: Optional[dict[str, Any]] = None) -> Job:
    """Two-phase parse (jobspec2): collect variable/locals blocks, then
    evaluate the job block with var/local in scope. ``variables`` overrides
    variable defaults (the -var CLI flag)."""
    try:
        body = hcl.parse(src)
    except hcl.HCLError as e:
        raise JobspecError(str(e)) from e

    base_ctx = hcl.EvalContext()
    var_values: dict[str, Any] = {}
    for vb in body.blocks_of("variable"):
        if not vb.labels:
            raise JobspecError("variable block requires a name label")
        name = vb.labels[0]
        if variables and name in variables:
            var_values[name] = variables[name]
        elif "default" in vb.body.attrs:
            var_values[name] = vb.body.attrs["default"].expr(base_ctx)
        else:
            raise JobspecError(f"variable {name!r} has no value")
    if variables:
        unknown = set(variables) - {vb.labels[0] for vb in body.blocks_of("variable")}
        if unknown:
            raise JobspecError(f"undeclared variables: {sorted(unknown)}")

    ctx = _jobspec_ctx(var_values, {})
    local_values: dict[str, Any] = {}
    for lb in body.blocks_of("locals") + body.blocks_of("local"):
        for name, attr in lb.body.attrs.items():
            local_values[name] = attr.expr(ctx)
    ctx = _jobspec_ctx(var_values, local_values)

    jb = body.first("job")
    if jb is None:
        raise JobspecError("no job block found")
    try:
        return parse_job(jb, ctx)
    except hcl.HCLError as e:
        raise JobspecError(str(e)) from e
