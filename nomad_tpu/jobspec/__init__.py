"""Jobspec — HCL job files → Job structs.

Reference: jobspec2/parse.go:19 (HCL2 with variables/locals/functions)
and jobspec/parse.go (stanza shapes).
"""

from .parse import JobspecError, parse_duration, parse_job, parse_job_file

__all__ = ["JobspecError", "parse_duration", "parse_job", "parse_job_file"]
