"""Agent — the single-binary composition of server and/or client.

Reference: command/agent/agent.go (:709 setupServer, :884 setupClient);
``nomad agent -dev`` runs both in one process with an in-process RPC link,
which is exactly what DevAgent does here.
"""

from __future__ import annotations

import tempfile
from typing import Optional

from .client import Client
from .server import Server, ServerConfig


class DevAgent:
    """Server + client in one process (the `-dev` mode)."""

    def __init__(
        self,
        data_dir: Optional[str] = None,
        num_workers: int = 2,
        heartbeat_ttl: float = 5.0,
        node=None,
        host_volumes: Optional[dict] = None,
        driver_mode: str = "inprocess",
        device_plugins: Optional[list] = None,
        csi_plugins: Optional[list] = None,
    ):
        self.data_dir = data_dir or tempfile.mkdtemp(prefix="nomad-tpu-dev-")
        self.server = Server(
            ServerConfig(num_workers=num_workers, heartbeat_ttl=heartbeat_ttl)
        )
        self.client = Client(
            rpc=self.server.client_rpc(),
            data_dir=self.data_dir,
            node=node,
            host_volumes=host_volumes,
            driver_mode=driver_mode,
            device_plugins=device_plugins,
            csi_plugins=csi_plugins,
        )

    def start(self) -> None:
        self.server.establish_leadership()
        self.client.start()

    def shutdown(self) -> None:
        self.client.shutdown()
        self.server.shutdown()

    # convenience passthroughs
    def register_job(self, job):
        return self.server.register_job(job)

    def deregister_job(self, namespace: str, job_id: str):
        return self.server.deregister_job(namespace, job_id)

    @property
    def store(self):
        return self.server.store
