"""Node fingerprinting: detect attributes, resources, and drivers.

Reference: client/fingerprint/ (~40 detectors: arch, cpu, memory, storage,
kernel, nomad version, drivers) orchestrated by client/fingerprint_manager.go.
Here one pass over procfs/os APIs fills the same attribute namespace
(``cpu.*``, ``memory.*``, ``kernel.*``, ``unique.*``, ``driver.*``).
"""

from __future__ import annotations

import multiprocessing
import os
import platform
import shutil
import socket
import uuid

from ..structs import Node, NodeResources

from .. import __version__


def _total_memory_mb() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) // 1024
    except OSError:
        pass
    return 4096


def _disk_mb(path: str = "/") -> int:
    try:
        st = os.statvfs(path)
        return int(st.f_frsize * st.f_blocks / (1024 * 1024))
    except OSError:
        return 50 * 1024


def _cpu_mhz() -> int:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    return int(float(line.split(":")[1]))
    except OSError:
        pass
    return 2000


def fingerprint_node(
    node: Node | None = None, *, data_dir: str = "", drivers=None
) -> Node:
    """Build (or refresh) a Node from the host. ``drivers`` is the driver
    registry used for driver.* attributes (client/fingerprint_manager.go
    fingerprints plugins through the same pass)."""
    node = node or Node(id=str(uuid.uuid4()))
    cores = multiprocessing.cpu_count()
    mhz = _cpu_mhz()
    node.name = node.name or socket.gethostname()
    node.attributes.update(
        {
            "kernel.name": platform.system().lower(),
            "kernel.version": platform.release(),
            "arch": platform.machine(),
            "os.name": platform.system().lower(),
            "cpu.numcores": str(cores),
            "cpu.frequency": str(mhz),
            "cpu.totalcompute": str(cores * mhz),
            "memory.totalbytes": str(_total_memory_mb() * 1024 * 1024),
            "nomad.version": __version__,
            "unique.hostname": socket.gethostname(),
            "unique.storage.volume": data_dir or "/tmp",
        }
    )
    node.node_resources = NodeResources(
        cpu=cores * mhz,
        memory_mb=_total_memory_mb(),
        disk_mb=_disk_mb(data_dir or "/"),
    )
    if drivers is not None:
        for name, drv in drivers.items():
            healthy = drv.fingerprint()
            node.drivers[name] = healthy
            node.attributes[f"driver.{name}"] = "1" if healthy else "0"
    node.compute_class()
    return node
