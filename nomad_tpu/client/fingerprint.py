"""Node fingerprinting: detect attributes, resources, and drivers.

Reference: client/fingerprint/ (~40 detectors orchestrated by
client/fingerprint_manager.go). This build runs a detector list over the
same attribute namespaces; each detector is isolated (a failing probe
never aborts fingerprinting, matching the manager's per-fingerprinter
error handling) and cheap-probe-first (cloud env detectors respect a
short timeout, like env_aws/env_gce do).

Detector parity map (reference file → here):
- cpu.go / memory.go / storage.go      → _fp_cpu, _fp_memory, _fp_storage
- arch.go / host.go / signal.go        → _fp_host
- network.go                           → _fp_network (iface, IP, speed)
- bridge.go / cni.go                   → _fp_bridge (kernel module probe)
- cgroup.go                            → _fp_cgroup (v1/v2 mountpoint)
- env_aws.go / env_gce.go / env_azure  → _fp_cloud (metadata endpoints;
  gated by NOMAD_TPU_CLOUD_FINGERPRINT — zero-egress hosts skip)
- consul.go / vault.go                 → _fp_consul_vault (env-var probes
  only; the integrations themselves are descoped)
- nomad.go                             → _fp_nomad
- plugins via manager                  → driver loop in fingerprint_node
- accelerators (plugins/device)        → _fp_tpu (this build's native
  accelerator is the TPU itself: jax device table when present)
"""

from __future__ import annotations

import glob
import hashlib
import multiprocessing
import os
import platform
import shutil
import socket
import uuid

from ..structs import Node, NodeResources
from ..structs.resources import NetworkResource

from .. import __version__


def _read(path: str) -> str:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return ""


def _total_memory_mb() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) // 1024
    except OSError:
        pass
    return 4096


def _disk_mb(path: str = "/") -> int:
    try:
        st = os.statvfs(path)
        return int(st.f_frsize * st.f_blocks / (1024 * 1024))
    except OSError:
        return 50 * 1024


def _cpu_mhz() -> int:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    return int(float(line.split(":")[1]))
    except OSError:
        pass
    return 2000


# -- detectors (client/fingerprint/*.go) -------------------------------------


def _fp_cpu(node: Node, ctx: dict) -> None:
    cores = multiprocessing.cpu_count()
    mhz = _cpu_mhz()
    model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    node.attributes.update(
        {
            "cpu.numcores": str(cores),
            "cpu.frequency": str(mhz),
            "cpu.totalcompute": str(cores * mhz),
        }
    )
    if model:
        node.attributes["cpu.modelname"] = model
    ctx["cpu"] = cores * mhz


def _fp_memory(node: Node, ctx: dict) -> None:
    mb = _total_memory_mb()
    node.attributes["memory.totalbytes"] = str(mb * 1024 * 1024)
    ctx["memory_mb"] = mb


def _fp_storage(node: Node, ctx: dict) -> None:
    path = ctx.get("data_dir") or "/"
    mb = _disk_mb(path)
    node.attributes.update(
        {
            "unique.storage.volume": path,
            "unique.storage.bytestotal": str(mb * 1024 * 1024),
            "unique.storage.bytesfree": str(
                _free_mb(path) * 1024 * 1024
            ),
        }
    )
    ctx["disk_mb"] = mb


def _free_mb(path: str) -> int:
    try:
        st = os.statvfs(path)
        return int(st.f_frsize * st.f_bavail / (1024 * 1024))
    except OSError:
        return 0


def _fp_host(node: Node, ctx: dict) -> None:
    node.attributes.update(
        {
            "kernel.name": platform.system().lower(),
            "kernel.version": platform.release(),
            "arch": platform.machine(),
            "os.name": platform.system().lower(),
            "os.version": platform.version(),
            "unique.hostname": socket.gethostname(),
        }
    )


def _fp_network(node: Node, ctx: dict) -> None:
    """network.go: default interface, its IP, and link speed (Mbits)."""
    iface, ip = None, None
    try:
        # the default-route trick: a UDP "connect" picks the egress iface
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("192.0.2.1", 9))  # TEST-NET: never actually sent
            ip = s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        pass
    speed = 1000
    for path in sorted(glob.glob("/sys/class/net/*")):
        name = os.path.basename(path)
        if name == "lo":
            continue
        if _read(os.path.join(path, "operstate")) == "up":
            iface = iface or name
            raw = _read(os.path.join(path, "speed"))
            if raw and raw.lstrip("-").isdigit() and int(raw) > 0:
                speed = int(raw)
            break
    if iface:
        node.attributes["unique.network.interface"] = iface
    if ip:
        node.attributes["unique.network.ip-address"] = ip
    node.attributes["network.speed"] = str(speed)
    ctx["net_speed"] = speed


def _fp_bridge(node: Node, ctx: dict) -> None:
    """bridge.go: is the kernel bridge module available?"""
    if os.path.isdir("/sys/class/net/docker0") or os.path.exists(
        "/sys/module/bridge"
    ):
        node.attributes["network.bridge"] = "1"


def _fp_cgroup(node: Node, ctx: dict) -> None:
    """cgroup.go: cgroup mountpoint + version (drives exec isolation)."""
    if os.path.isdir("/sys/fs/cgroup"):
        v2 = os.path.exists("/sys/fs/cgroup/cgroup.controllers")
        node.attributes["unique.cgroup.mountpoint"] = "/sys/fs/cgroup"
        node.attributes["unique.cgroup.version"] = "v2" if v2 else "v1"


def _fp_cloud(node: Node, ctx: dict) -> None:
    """env_aws/env_gce/env_azure: cloud metadata — network probes are
    gated (zero-egress hosts must not stall fingerprinting); cheap
    filesystem hints run unconditionally."""
    vendor = _read("/sys/class/dmi/id/sys_vendor").lower()
    product = _read("/sys/class/dmi/id/product_name").lower()
    if "amazon" in vendor or "ec2" in product:
        node.attributes["platform.aws"] = "1"
    elif "google" in vendor or "google" in product:
        node.attributes["platform.gce"] = "1"
    elif "microsoft" in vendor:
        node.attributes["platform.azure"] = "1"
    if os.environ.get("NOMAD_TPU_CLOUD_FINGERPRINT") != "1":
        return
    # full metadata probes (169.254.169.254) only when explicitly enabled


def _fp_consul_vault(node: Node, ctx: dict) -> None:
    """consul.go/vault.go reduced to env discovery (integration descoped;
    the attributes still drive constraints)."""
    if os.environ.get("CONSUL_HTTP_ADDR"):
        node.attributes["consul.addr"] = os.environ["CONSUL_HTTP_ADDR"]
    if os.environ.get("VAULT_ADDR"):
        node.attributes["vault.addr"] = os.environ["VAULT_ADDR"]


def _fp_nomad(node: Node, ctx: dict) -> None:
    node.attributes["nomad.version"] = __version__
    node.attributes["nomad.revision"] = "tpu-native"


def _fp_tpu(node: Node, ctx: dict) -> None:
    """Accelerator detection — this build's native accelerator is the
    TPU: surface the jax device table when a backend is already live
    (never initializes jax itself; that is the scheduler's decision)."""
    import sys

    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return
    try:
        devices = jax_mod.devices()
    except Exception:
        return
    accel = [d for d in devices if d.platform not in ("cpu",)]
    if accel:
        node.attributes["tpu.count"] = str(len(accel))
        node.attributes["tpu.type"] = getattr(
            accel[0], "device_kind", accel[0].platform
        )


def normalize_device_class(kind: str) -> str:
    """Canonicalize an accelerator kind string into a device class slug:
    lowercase, spaces → dashes, ``TPU v5e`` → ``tpu-v5e``,
    ``NVIDIA A100`` → ``gpu-a100``-style names pass through as typed."""
    slug = "-".join(str(kind).strip().lower().split())
    return slug


def _fp_device_class(node: Node, ctx: dict) -> None:
    """Heterogeneity fingerprint: derive ``node.device_class`` from the
    detected accelerator (``tpu.type`` from _fp_tpu), with an explicit
    ``NOMAD_TPU_DEVICE_CLASS`` operator override winning. Hosts with no
    accelerator stay class-less ("") so existing clusters schedule
    bit-identically until an operator opts a fleet in."""
    override = os.environ.get("NOMAD_TPU_DEVICE_CLASS", "")
    if override:
        node.device_class = normalize_device_class(override)
        node.attributes["device.class"] = node.device_class
        return
    if node.device_class:
        # pre-configured (client config) — keep, but surface as an attr
        node.attributes["device.class"] = node.device_class
        return
    kind = node.attributes.get("tpu.type", "")
    if kind:
        slug = normalize_device_class(kind)
        if not slug.startswith(("tpu", "gpu")):
            slug = f"tpu-{slug}"
        node.device_class = slug
        node.attributes["device.class"] = slug


TOPOLOGY_LEVELS = ("rack", "pod", "ici")


def normalize_topology(spec: str) -> dict[str, str]:
    """Parse a ``rack=r03,pod=p1,ici=2.1`` coordinate spec into a
    topology dict. Unknown levels and malformed entries are dropped —
    an operator typo degrades to topology-less, never to a crash."""
    topo: dict[str, str] = {}
    for entry in str(spec).split(","):
        if "=" not in entry:
            continue
        level, _, value = entry.partition("=")
        level = level.strip().lower()
        value = value.strip().lower()
        if level in TOPOLOGY_LEVELS and value:
            topo[level] = value
    return topo


def _fp_topology(node: Node, ctx: dict) -> None:
    """Topology fingerprint: rack/pod/ICI coordinates for gang-aware
    placement. Precedence mirrors _fp_device_class: an explicit
    ``NOMAD_TPU_TOPOLOGY`` operator override wins, then pre-configured
    coordinates (client config), then — when an accelerator was detected
    (``tpu.type`` from _fp_tpu) — a deterministic derivation from the
    node name, so a fleet brought up without cabling data still gets
    stable, restart-invariant coordinates. Hosts with no accelerator and
    no override stay topology-less ({}) so existing clusters schedule
    bit-identically until an operator opts a fleet in."""
    override = os.environ.get("NOMAD_TPU_TOPOLOGY", "")
    if override:
        topo = normalize_topology(override)
        if topo:
            node.topology = topo
            for level, value in topo.items():
                node.attributes[f"topology.{level}"] = value
        return
    if node.topology:
        # pre-configured (client config) — keep, but surface as attrs
        for level, value in node.topology.items():
            node.attributes[f"topology.{level}"] = value
        return
    if not node.attributes.get("tpu.type", ""):
        return
    # derive stable coordinates from the node identity: 16 racks of a
    # 4-pod fabric, ICI coordinate = (pod, rack-within-pod). blake2b of
    # the name (not the uuid) so a re-registered host keeps its slot.
    h = int.from_bytes(
        hashlib.blake2b((node.name or node.id).encode(), digest_size=4).digest(),
        "big",
    )
    rack = h % 16
    pod = (h >> 8) % 4
    node.topology = {
        "rack": f"r{rack:02d}",
        "pod": f"p{pod}",
        "ici": f"{pod}.{rack % 4}",
    }
    for level, value in node.topology.items():
        node.attributes[f"topology.{level}"] = value


DETECTORS = (
    _fp_cpu,
    _fp_memory,
    _fp_storage,
    _fp_host,
    _fp_network,
    _fp_bridge,
    _fp_cgroup,
    _fp_cloud,
    _fp_consul_vault,
    _fp_nomad,
    _fp_tpu,
    _fp_device_class,  # after _fp_tpu: consumes its tpu.type attribute
    _fp_topology,  # after _fp_tpu: gates derivation on its tpu.type
)


def fingerprint_node(
    node: Node | None = None, *, data_dir: str = "", drivers=None
) -> Node:
    """Build (or refresh) a Node from the host. ``drivers`` is the driver
    registry used for driver.* attributes (client/fingerprint_manager.go
    fingerprints plugins through the same pass). Detector failures are
    isolated per fingerprinter, as in the manager."""
    node = node or Node(id=str(uuid.uuid4()))
    node.name = node.name or socket.gethostname()
    ctx: dict = {"data_dir": data_dir}
    for det in DETECTORS:
        try:
            det(node, ctx)
        except Exception:  # noqa: BLE001 — a probe must never kill startup
            pass
    node.node_resources = NodeResources(
        cpu=ctx.get("cpu", 4000),
        memory_mb=ctx.get("memory_mb", 4096),
        disk_mb=ctx.get("disk_mb", 50 * 1024),
        networks=[NetworkResource(mbits=ctx.get("net_speed", 1000))],
    )
    if drivers is not None:
        for name, drv in drivers.items():
            healthy = drv.fingerprint()
            node.drivers[name] = healthy
            node.attributes[f"driver.{name}"] = "1" if healthy else "0"
    node.compute_class()
    return node
