"""Task drivers — the execution plugins.

Reference: the TaskDriver gRPC contract (plugins/drivers/driver.go,
plugins/drivers/proto/driver.proto: Start/Wait/Stop/Inspect/Recover) and
the built-in drivers (drivers/{mock,rawexec,exec}). The contract here is
the same shape, in-process for the built-ins; out-of-process gRPC plugins
slot in behind the same ``TaskDriver`` interface (the executor subprocess
the reference re-execs, drivers/shared/executor, maps to the C++ executor
planned for the native runtime layer).

- ``mock_driver``: deterministic fake (run_for / exit_code / start_error)
  — the workhorse of client tests, mirroring drivers/mock.
- ``raw_exec`` / ``exec``: fork/exec of task.config["command"]+["args"]
  with env + alloc dir plumbing. (``exec`` currently shares raw_exec's
  no-isolation path; chroot/cgroup isolation is the C++ executor's job.)
"""

from __future__ import annotations

import os
import resource as _resource  # imported pre-fork: preexec_fn must not import
import shutil
import signal
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_EXECUTOR_BIN = os.path.join(_NATIVE_DIR, "build", "executor")
_executor_checked = False
_executor_lock = threading.Lock()


def native_executor() -> Optional[str]:
    """Path to the C++ task supervisor (native/executor.cpp — the
    drivers/shared/executor analog), built lazily like the WAL store
    (nomad_tpu/native/wal.py _load, same serialized-build discipline: a
    concurrent caller must never exec a half-linked binary). None when
    the toolchain is unavailable (pure-Python isolation then applies)."""
    global _executor_checked
    src = os.path.join(_NATIVE_DIR, "executor.cpp")
    with _executor_lock:
        if not _executor_checked:
            if os.path.exists(src) and (
                not os.path.exists(_EXECUTOR_BIN)
                or os.path.getmtime(_EXECUTOR_BIN) < os.path.getmtime(src)
            ):
                try:
                    os.makedirs(os.path.dirname(_EXECUTOR_BIN), exist_ok=True)
                    tmp = _EXECUTOR_BIN + ".tmp"
                    subprocess.run(
                        ["g++", "-O2", "-std=c++17", "-Wall", "-o", tmp, src],
                        check=True,
                        capture_output=True,
                        timeout=120,
                    )
                    os.replace(tmp, _EXECUTOR_BIN)
                except Exception:
                    # cache the failure: re-attempting a broken build on
                    # every task start would stall starts behind the lock
                    _executor_checked = True
                    return None
            _executor_checked = True
        return _EXECUTOR_BIN if os.path.exists(_EXECUTOR_BIN) else None


def _proc_start_time(pid: int):
    """Kernel start time (clock ticks since boot) from /proc — the
    identity that distinguishes a live task from a recycled PID."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read()
        # field 22, counting from 1 after the parenthesized comm
        return int(data.rsplit(b")", 1)[1].split()[19])
    except (OSError, ValueError, IndexError):
        return None


TASK_STATE_PENDING = "pending"
TASK_STATE_RUNNING = "running"
TASK_STATE_DEAD = "dead"


@dataclass
class TaskHandle:
    """Reattachable task handle (plugins/drivers/task_handle.go)."""

    id: str
    driver: str
    pid: int = 0
    state: str = TASK_STATE_RUNNING
    exit_code: Optional[int] = None
    started_at: float = field(default_factory=time.time)
    completed_at: float = 0.0
    meta: dict = field(default_factory=dict)


class DriverError(Exception):
    pass


class TaskDriver:
    name = "base"

    def fingerprint(self) -> bool:
        return True

    def start(self, task, env: dict, task_dir: str) -> TaskHandle:
        raise NotImplementedError

    def wait(self, handle: TaskHandle, timeout: Optional[float] = None) -> Optional[int]:
        """Block until exit; returns exit code (None on timeout)."""
        raise NotImplementedError

    def stop(self, handle: TaskHandle, kill_timeout: float = 5.0) -> None:
        raise NotImplementedError

    def inspect(self, handle: TaskHandle) -> TaskHandle:
        return handle

    def recover(self, handle: TaskHandle) -> bool:
        """Re-attach to a task that survived a client restart
        (plugins/drivers/task_handle.go reattach tokens; client/state
        restore path task_runner.go:488-519). Returns False when the task
        cannot be recovered (caller restarts it per policy)."""
        return False


class MockDriver(TaskDriver):
    """drivers/mock: configurable timing/failure knobs via task.config:
    run_for (s), exit_code, start_error, start_block_for (s)."""

    name = "mock_driver"

    def __init__(self):
        self._events: dict[str, threading.Event] = {}
        self._handles: dict[str, TaskHandle] = {}

    def start(self, task, env, task_dir) -> TaskHandle:
        cfg = task.config or {}
        if cfg.get("start_error"):
            raise DriverError(str(cfg["start_error"]))
        if cfg.get("start_block_for"):
            time.sleep(float(cfg["start_block_for"]))
        h = TaskHandle(id=str(uuid.uuid4()), driver=self.name)
        h.meta["run_for"] = float(cfg.get("run_for", 0.0))
        h.meta["exit_code"] = int(cfg.get("exit_code", 0))
        h.meta["deadline"] = h.started_at + h.meta["run_for"]
        self._events[h.id] = threading.Event()
        self._handles[h.id] = h
        return h

    def wait(self, handle, timeout=None):
        remaining = handle.meta["deadline"] - time.time()
        stop_evt = self._events.get(handle.id)
        waited = stop_evt.wait(max(remaining, 0)) if stop_evt else False
        if timeout is not None and remaining > timeout:
            return None
        handle.state = TASK_STATE_DEAD
        handle.completed_at = time.time()
        handle.exit_code = 130 if waited else handle.meta["exit_code"]
        return handle.exit_code

    def stop(self, handle, kill_timeout=5.0):
        evt = self._events.get(handle.id)
        if evt:
            evt.set()


class RawExecDriver(TaskDriver):
    """drivers/rawexec: no isolation, direct fork/exec."""

    name = "raw_exec"

    def __init__(self):
        self._procs: dict[str, subprocess.Popen] = {}

    def fingerprint(self) -> bool:
        return os.name == "posix"

    def start(self, task, env, task_dir) -> TaskHandle:
        cfg = task.config or {}
        command = cfg.get("command")
        if not command:
            raise DriverError("raw_exec requires config['command']")
        argv = [command] + list(cfg.get("args", []))
        stdout = open(os.path.join(task_dir, f"{task.name}.stdout"), "ab")
        stderr = open(os.path.join(task_dir, f"{task.name}.stderr"), "ab")
        try:
            proc = subprocess.Popen(
                argv,
                cwd=task_dir,
                env={**os.environ, **env},
                stdout=stdout,
                stderr=stderr,
                start_new_session=True,  # own process group for clean kill
            )
        except OSError as e:
            raise DriverError(f"failed to exec {command}: {e}") from e
        finally:
            stdout.close()
            stderr.close()
        h = TaskHandle(id=str(uuid.uuid4()), driver=self.name, pid=proc.pid)
        h.meta["proc_start"] = _proc_start_time(proc.pid)
        self._procs[h.id] = proc
        return h

    def recover(self, handle: TaskHandle) -> bool:
        """Re-attach by pid + kernel start time: a recycled PID must not
        re-attach to (and later SIGTERM) an unrelated process. (The
        reference re-attaches to its executor subprocess, which owns the
        child and its eventual exit status; without an owning process a
        recovered task's exit code is unobservable and reads as 0.)"""
        if handle.pid <= 0:
            return False
        try:
            os.kill(handle.pid, 0)
        except (ProcessLookupError, PermissionError):
            return False
        want = handle.meta.get("proc_start")
        if want is not None and _proc_start_time(handle.pid) != want:
            return False  # same pid, different process: recycled
        handle.meta["recovered"] = True
        return True

    def wait(self, handle, timeout=None):
        proc = self._procs.get(handle.id)
        if proc is None:
            if handle.meta.get("recovered") and handle.pid > 0:
                # not our child: poll for process-group exit
                deadline = None if timeout is None else time.time() + timeout
                while True:
                    try:
                        os.kill(handle.pid, 0)
                    except ProcessLookupError:
                        handle.state = TASK_STATE_DEAD
                        handle.exit_code = 0  # unobservable post-reattach
                        handle.completed_at = time.time()
                        return 0
                    if deadline is not None and time.time() >= deadline:
                        return None
                    time.sleep(0.1)
            return handle.exit_code
        try:
            code = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None
        handle.state = TASK_STATE_DEAD
        handle.exit_code = code
        handle.completed_at = time.time()
        return code

    def stop(self, handle, kill_timeout=5.0):
        proc = self._procs.get(handle.id)
        if proc is None:
            if handle.meta.get("recovered") and handle.pid > 0:
                try:
                    os.killpg(handle.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
            return
        if proc.poll() is not None:
            return
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            return
        try:
            proc.wait(timeout=kill_timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass


class ExecDriver(RawExecDriver):
    """drivers/exec — isolated execution.

    The reference's exec driver runs tasks under a libcontainer-based
    executor subprocess (drivers/shared/executor: chroot, cgroups,
    namespaces). This build applies the portable subset of that
    isolation in-process: own session (setsid, inherited from raw_exec's
    start_new_session), resource rlimits derived from the task's
    resource ask (address space from memory_mb, no core dumps, bounded
    fd/proc counts), and a scrubbed environment — the task sees only its
    Nomad env plus a minimal PATH, not the agent's environment.
    cgroup/chroot confinement belongs to the native executor layer."""

    name = "exec"

    def start(self, task, env, task_dir) -> TaskHandle:
        cfg = task.config or {}
        command = cfg.get("command")
        if not command:
            raise DriverError("exec requires config['command']")
        argv = [command] + list(cfg.get("args", []))
        mem_mb = 256
        res = getattr(task, "resources", None)
        if res is not None and getattr(res, "memory_mb", 0):
            mem_mb = int(res.memory_mb)

        supervisor = native_executor()
        if supervisor:
            return self._start_supervised(
                supervisor, task, argv, env, task_dir, mem_mb
            )

        def _isolate():
            # post-fork pre-exec: no imports, no locks (the agent is
            # multithreaded; only async-signal-safe-ish work is allowed)
            rl = _resource
            # headroom over the ask: AS counts virtual, not resident,
            # memory — a tight bound would kill interpreters at startup
            limit = (mem_mb + 512) * 1024 * 1024
            rl.setrlimit(rl.RLIMIT_AS, (limit, limit))
            rl.setrlimit(rl.RLIMIT_CORE, (0, 0))
            try:
                rl.setrlimit(rl.RLIMIT_NPROC, (512, 512))
            except (ValueError, OSError):
                pass  # lower hard limit already in place

        stdout = open(os.path.join(task_dir, f"{task.name}.stdout"), "ab")
        stderr = open(os.path.join(task_dir, f"{task.name}.stderr"), "ab")
        try:
            proc = subprocess.Popen(
                argv,
                cwd=task_dir,
                env={
                    "PATH": "/usr/local/bin:/usr/bin:/bin",
                    "HOME": task_dir,
                    "TMPDIR": task_dir,
                    **env,
                },
                stdout=stdout,
                stderr=stderr,
                start_new_session=True,
                preexec_fn=_isolate,
            )
        except OSError as e:
            raise DriverError(f"failed to exec {command}: {e}") from e
        finally:
            stdout.close()
            stderr.close()
        h = TaskHandle(id=str(uuid.uuid4()), driver=self.name, pid=proc.pid)
        h.meta["proc_start"] = _proc_start_time(proc.pid)
        self._procs[h.id] = proc
        return h

    # -- native supervisor path (drivers/shared/executor analog) ----------
    def _start_supervised(
        self, supervisor, task, argv, env, task_dir, mem_mb
    ) -> TaskHandle:
        """Run through the C++ executor: it owns the task child, applies
        the isolation, and records the exit status durably, so re-attach
        after an agent restart observes real exit codes."""
        status_file = os.path.join(task_dir, f"{task.name}.status")
        # a prior run of the same task left its record at the same path;
        # it must never be read as THIS run's status
        try:
            os.unlink(status_file)
        except OSError:
            pass
        grace = int(getattr(task, "kill_timeout_s", 5.0) or 5.0)
        handle_id = str(uuid.uuid4())
        extra: list[str] = []
        # per-task cgroup (drivers/shared/executor cgroup confinement):
        # only meaningful when this process may create cgroups — probe
        # once per driver instance
        if self._cgroups_available():
            extra += ["--cgroup", handle_id[:18]]
            res = getattr(task, "resources", None)
            if res is not None and getattr(res, "cpu", 0):
                extra += ["--cpu-mhz", str(int(res.cpu))]
        try:
            proc = subprocess.Popen(
                [
                    supervisor,
                    task_dir,
                    os.path.join(task_dir, f"{task.name}.stdout"),
                    os.path.join(task_dir, f"{task.name}.stderr"),
                    status_file,
                    str(mem_mb),
                    str(grace),
                ]
                + extra
                + ["--"]
                + argv,
                cwd=task_dir,
                env={
                    "PATH": "/usr/local/bin:/usr/bin:/bin",
                    "HOME": task_dir,
                    "TMPDIR": task_dir,
                    **env,
                },
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                start_new_session=True,
            )
        except OSError as e:
            raise DriverError(f"failed to exec supervisor: {e}") from e
        h = TaskHandle(id=handle_id, driver=self.name, pid=proc.pid)
        h.meta["proc_start"] = _proc_start_time(proc.pid)
        h.meta["status_file"] = status_file
        h.meta["supervised"] = True
        h.meta["grace_s"] = float(grace)
        self._procs[h.id] = proc
        return h

    @staticmethod
    def _cgroups_available() -> bool:
        """Can this agent create task cgroups? v2 unified with memory
        delegated, or v1 memory hierarchy, writable by us."""
        try:
            with open("/sys/fs/cgroup/cgroup.controllers") as f:
                if "memory" in f.read():
                    return os.access("/sys/fs/cgroup", os.W_OK)
        except OSError:
            pass
        return os.access("/sys/fs/cgroup/memory", os.W_OK)

    def _read_status_raw(self, handle) -> tuple[str, Optional[int], Optional[int]]:
        """The supervisor's durable status record:
        ('running', child_pid, child_start_ticks) or ('exit', code, None)
        or ('', None, None) when absent/unreadable."""
        try:
            with open(handle.meta["status_file"]) as f:
                parts = f.read().strip().split()
            word = parts[0]
            val = int(parts[1])
            extra = int(parts[2]) if len(parts) > 2 else None
            return word, val, extra
        except (OSError, KeyError, ValueError, IndexError):
            return "", None, None

    def _read_status(self, handle) -> Optional[int]:
        word, val, _ = self._read_status_raw(handle)
        return val if word == "exit" else None

    def recover(self, handle: TaskHandle) -> bool:
        if handle.meta.get("supervised"):
            # supervisor alive → live re-attach; dead → the status file
            # still tells us how the task ended (the durability the
            # reference gets from its executor process, task_handle.go)
            if super().recover(handle):
                return True
            word, val, start_ticks = self._read_status_raw(handle)
            if word == "exit":
                handle.state = TASK_STATE_DEAD
                handle.exit_code = val
                handle.completed_at = handle.completed_at or time.time()
                handle.meta["recovered"] = True
                return True
            if word == "running" and val:
                # supervisor died out from under a live task: reap the
                # orphan before the restart policy launches a fresh copy
                # (two concurrent runs of the workload otherwise) — but
                # ONLY if the pid still belongs to that task (a recycled
                # pid must never be signalled; the supervisor recorded
                # the child's kernel start time for exactly this check —
                # records without usable ticks fall back to a liveness
                # check, accepting the small recycled-pid risk over a
                # guaranteed dual-run of the workload)
                now_ticks = _proc_start_time(val)
                if now_ticks is not None and (
                    not start_ticks or now_ticks == start_ticks
                ):
                    try:
                        os.killpg(val, signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass
            return False
        return super().recover(handle)

    def wait(self, handle, timeout=None):
        if not handle.meta.get("supervised"):
            return super().wait(handle, timeout)
        if handle.state == TASK_STATE_DEAD:
            return handle.exit_code
        proc = self._procs.get(handle.id)
        if proc is not None:
            try:
                code = proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                return None
            # the supervisor exits with the child's code; prefer the
            # status file (survives supervisor signals)
            rec = self._read_status(handle)
            code = rec if rec is not None else code
            handle.state = TASK_STATE_DEAD
            handle.exit_code = code
            handle.completed_at = time.time()
            return code
        # re-attached: poll the status file while the supervisor lives
        deadline = None if timeout is None else time.time() + timeout
        while True:
            code = self._read_status(handle)
            if code is not None:
                handle.state = TASK_STATE_DEAD
                handle.exit_code = code
                handle.completed_at = time.time()
                return code
            try:
                os.kill(handle.pid, 0)
            except ProcessLookupError:
                # the supervisor may have written its exit record in the
                # window between the read above and this probe
                code = self._read_status(handle)
                handle.state = TASK_STATE_DEAD
                handle.exit_code = (
                    code if code is not None else (handle.exit_code or 0)
                )
                handle.completed_at = handle.completed_at or time.time()
                return handle.exit_code
            if deadline is not None and time.time() >= deadline:
                return None
            time.sleep(0.1)

    def stop(self, handle, kill_timeout=5.0):
        if not handle.meta.get("supervised"):
            return super().stop(handle, kill_timeout)
        if handle.state == TASK_STATE_DEAD:
            return  # already terminal (e.g. recovered via status record)
        proc = self._procs.get(handle.id)
        if proc is None:
            # re-attached: verify pid identity before signalling — the
            # recorded pid may have been recycled by an unrelated process
            want = handle.meta.get("proc_start")
            if want is None or _proc_start_time(handle.pid) != want:
                return
        # SIGTERM the supervisor; it forwards to the task's process group
        # with the configured grace period (executor.cpp forward_term)
        try:
            os.kill(handle.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        grace = max(kill_timeout, handle.meta.get("grace_s", 5.0)) + 6.0
        proc = self._procs.get(handle.id)
        if proc is not None:
            try:
                proc.wait(timeout=grace)
            except subprocess.TimeoutExpired:
                self._hard_kill_supervised(handle)
        else:
            deadline = time.time() + grace
            while time.time() < deadline:
                # the durable status record is authoritative — the pid
                # may linger as a zombie under another holder
                if self._read_status(handle) is not None:
                    return
                try:
                    os.kill(handle.pid, 0)
                except ProcessLookupError:
                    return
                time.sleep(0.1)
            self._hard_kill_supervised(handle)

    def _hard_kill_supervised(self, handle) -> None:
        """Escalation targets the TASK's process group (from the status
        record) — SIGKILLing only the supervisor would orphan a live
        child in its own session and freeze the status at 'running'."""
        word, val, start_ticks = self._read_status_raw(handle)
        if word == "running" and val and (
            not start_ticks or _proc_start_time(val) == start_ticks
        ):
            try:
                os.killpg(val, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        try:
            os.kill(handle.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


class JavaDriver(ExecDriver):
    """drivers/java — JVM workloads under the shared executor.

    The reference driver (drivers/java/driver.go) synthesizes the java
    argv from the task config and hands it to the isolated executor; so
    does this one: ``jar_path`` (→ ``-jar``) or ``class``/``class_path``
    (→ ``-cp``), plus ``jvm_options`` and ``args``. JVM heap defaults to
    the task's memory ask (-Xmx) as the reference does."""

    name = "java"

    def fingerprint(self) -> bool:
        return shutil.which("java") is not None

    def start(self, task, env, task_dir) -> TaskHandle:
        import copy

        cfg = task.config or {}
        java = shutil.which("java")
        if java is None:
            raise DriverError("java runtime not found")
        # absolute path: the executor runs tasks with a scrubbed PATH
        argv = [java]
        res = getattr(task, "resources", None)
        if res is not None and getattr(res, "memory_mb", 0):
            # heap gets ~80% of the ask, capped at ask−32MB: the
            # executor's cgroup limit is the FULL ask, and heap == limit
            # leaves no room for metaspace/stacks — the kernel would
            # SIGKILL instead of the JVM raising OutOfMemoryError
            mem = int(res.memory_mb)
            heap = max(32, min(int(mem * 0.8), mem - 32))
            argv.append(f"-Xmx{heap}m")
        argv += list(cfg.get("jvm_options", []))
        if cfg.get("jar_path"):
            argv += ["-jar", cfg["jar_path"]]
        elif cfg.get("class"):
            if cfg.get("class_path"):
                argv += ["-cp", cfg["class_path"]]
            argv.append(cfg["class"])
        else:
            raise DriverError(
                "java requires config['jar_path'] or config['class']"
            )
        argv += list(cfg.get("args", []))
        synth = copy.copy(task)
        synth.config = {"command": argv[0], "args": argv[1:]}
        return super().start(synth, env, task_dir)


class QemuDriver(ExecDriver):
    """drivers/qemu — VM images under the shared executor.

    The reference (drivers/qemu/driver.go) execs qemu-system-x86_64 with
    the image, the task's memory ask, -nographic, and optional
    accelerator/port args; the VM process is supervised exactly like any
    exec task (the executor's cgroup/rlimit bounds apply to the VMM)."""

    name = "qemu"
    QEMU_BIN = "qemu-system-x86_64"

    def fingerprint(self) -> bool:
        return shutil.which(self.QEMU_BIN) is not None

    def start(self, task, env, task_dir) -> TaskHandle:
        import copy

        cfg = task.config or {}
        image = cfg.get("image_path")
        if not image:
            raise DriverError("qemu requires config['image_path']")
        mem_mb = 512
        res = getattr(task, "resources", None)
        if res is not None and getattr(res, "memory_mb", 0):
            mem_mb = int(res.memory_mb)
        # guest RAM below the cgroup cap: the VMM's own overhead
        # (~100-200MB) rides inside the same limit; small asks keep a
        # proportional margin instead of a fixed floor that would eat
        # the whole cap
        guest_mb = (
            mem_mb - 128 if mem_mb >= 256 else max(32, mem_mb // 2)
        )
        qemu = shutil.which(self.QEMU_BIN)
        if qemu is None:
            raise DriverError(f"{self.QEMU_BIN} not found")
        argv = [
            qemu,  # absolute: the executor scrubs PATH
            "-machine", "type=pc,accel=" + cfg.get("accelerator", "tcg"),
            "-m", f"{guest_mb}M",
            "-drive", f"file={image}",
            "-nographic",
        ]
        argv += list(cfg.get("args", []))
        synth = copy.copy(task)
        synth.config = {"command": argv[0], "args": argv[1:]}
        return super().start(synth, env, task_dir)


def builtin_drivers() -> dict[str, TaskDriver]:
    """The in-process driver catalog (helper/pluginutils/catalog analog)."""
    from .container import ContainerDriver

    return {
        d.name: d
        for d in (
            MockDriver(),
            RawExecDriver(),
            ExecDriver(),
            ContainerDriver(),
            JavaDriver(),
            QemuDriver(),
        )
    }
