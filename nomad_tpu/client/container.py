"""Container driver — docker/podman over the Engine HTTP API.

Reference: drivers/docker/ (10.8k LoC; driver.go StartTask/WaitTask/
StopTask/RecoverTask, driver_linux.go resource plumbing). The reference
links the Docker SDK; this driver speaks the Engine REST API directly
over the daemon's unix socket (podman serves the same API at
/run/podman/podman.sock), so it needs no vendored SDK and works against
either runtime.

Key properties matched to the reference:

- **Reattach by container id** (docker/handle.go): the container id IS
  the durable handle — after a client (or plugin subprocess) restart,
  ``recover()`` re-inspects the id; a still-running container re-attaches
  losslessly, an exited one yields its REAL exit code from the daemon
  (the daemon plays the role the native C++ supervisor plays for exec
  tasks: the process that outlives the agent and owns the exit status).
- **Resource plumbing** (driver_linux.go): the task's cpu/memory ask maps
  to HostConfig.NanoCpus / Memory — enforced by the runtime's cgroups.
- **Alloc dir bind** (docker/driver.go allocDir mounts): the task dir is
  bind-mounted at /alloc inside the container.
- **Log capture**: on exit the daemon's log endpoint is drained into the
  task dir's ``<task>.stdout`` / ``.stderr`` so the fs/logs HTTP
  endpoints serve container logs exactly like exec-task logs.

Out-of-process: the driver is registered in the builtin catalog, so
``python -m nomad_tpu.client.plugin container`` serves it over the
NDJSON stdio plugin protocol (client/plugin.py) — the same lifecycle,
reattach-through-plugin-death included, as every other plugin driver.

Socket discovery order: $NOMAD_CONTAINER_SOCK, /var/run/docker.sock,
/run/podman/podman.sock. Fingerprint is unhealthy when none answers
``GET /version`` — the driver is always present, never schedulable
without a live daemon (fingerprint.go semantics).
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import time
from typing import Optional

from .drivers import (
    DriverError,
    TASK_STATE_DEAD,
    TASK_STATE_RUNNING,
    TaskDriver,
    TaskHandle,
)

DEFAULT_SOCKETS = (
    "/var/run/docker.sock",
    "/run/podman/podman.sock",
)


class _UnixHTTPConnection(http.client.HTTPConnection):
    """http.client over AF_UNIX (the Engine API listens on a socket,
    not TCP)."""

    def __init__(self, sock_path: str, timeout: float = 60.0):
        super().__init__("localhost", timeout=timeout)
        self._sock_path = sock_path

    def connect(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self._sock_path)
        self.sock = s


class ContainerAPI:
    """Minimal Engine API client: exactly the endpoints the driver's
    lifecycle needs."""

    def __init__(self, sock_path: str, timeout: float = 60.0):
        self.sock_path = sock_path
        self.timeout = timeout

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        timeout: Optional[float] = None,
        raw: bool = False,
    ):
        conn = _UnixHTTPConnection(
            self.sock_path, timeout=timeout or self.timeout
        )
        try:
            data = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if data else {}
            conn.request(method, path, body=data, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status >= 400:
                try:
                    msg = json.loads(payload).get("message", "")
                except (ValueError, AttributeError):
                    msg = payload[:200].decode("utf-8", "replace")
                raise DriverError(
                    f"container daemon {method} {path}: "
                    f"{resp.status} {msg}"
                )
            if raw:
                return payload
            if not payload:
                return None
            try:
                return json.loads(payload)
            except ValueError:
                return payload
        finally:
            conn.close()

    def version(self) -> dict:
        return self._request("GET", "/version") or {}

    def pull(self, image: str) -> None:
        # POST /images/create streams progress; drain it
        self._request(
            "POST",
            f"/images/create?fromImage={image}",
            raw=True,
            timeout=600.0,
        )

    def create(self, spec: dict, name: str = "") -> str:
        q = f"?name={name}" if name else ""
        out = self._request("POST", f"/containers/create{q}", body=spec)
        return out["Id"]

    def start(self, cid: str) -> None:
        self._request("POST", f"/containers/{cid}/start")

    def wait(self, cid: str, timeout: Optional[float] = None) -> Optional[int]:
        try:
            out = self._request(
                "POST", f"/containers/{cid}/wait", timeout=timeout
            )
        except (socket.timeout, TimeoutError):
            return None
        except OSError as e:
            raise DriverError(f"container wait failed: {e}") from e
        return int(out.get("StatusCode", 0)) if out else 0

    def stop(self, cid: str, grace_s: float) -> None:
        self._request(
            "POST",
            f"/containers/{cid}/stop?t={int(grace_s)}",
            timeout=grace_s + 15.0,
        )

    def remove(self, cid: str) -> None:
        self._request("DELETE", f"/containers/{cid}?force=1&v=1")

    def inspect(self, cid: str) -> Optional[dict]:
        try:
            return self._request("GET", f"/containers/{cid}/json")
        except DriverError as e:
            if "404" in str(e):
                return None
            raise

    def logs(self, cid: str, stderr: bool = False) -> bytes:
        which = "stderr=1" if stderr else "stdout=1"
        return (
            self._request(
                "GET", f"/containers/{cid}/logs?{which}", raw=True
            )
            or b""
        )


def discover_socket() -> Optional[str]:
    env = os.environ.get("NOMAD_CONTAINER_SOCK")
    candidates = (env,) + DEFAULT_SOCKETS if env else DEFAULT_SOCKETS
    for path in candidates:
        if path and os.path.exists(path):
            return path
    return None


class ContainerDriver(TaskDriver):
    """drivers/docker analog over the Engine REST API."""

    name = "container"

    def __init__(self, sock_path: Optional[str] = None):
        self._sock_override = sock_path
        self._api: Optional[ContainerAPI] = None

    def _resolve_api(self) -> Optional[ContainerAPI]:
        if self._api is not None:
            return self._api
        path = self._sock_override or discover_socket()
        if path is None:
            return None
        self._api = ContainerAPI(path)
        return self._api

    @property
    def api(self) -> ContainerAPI:
        api = self._resolve_api()
        if api is None:
            raise DriverError(
                "no container daemon socket (set NOMAD_CONTAINER_SOCK or "
                "run docker/podman)"
            )
        return api

    def fingerprint(self) -> bool:
        api = self._resolve_api()
        if api is None:
            return False
        try:
            api.version()
            return True
        except (DriverError, OSError):
            # a vanished socket must re-resolve on the next probe
            self._api = None
            return False

    # -- lifecycle ---------------------------------------------------------
    def start(self, task, env, task_dir) -> TaskHandle:
        cfg = task.config or {}
        image = cfg.get("image")
        if not image:
            raise DriverError("container driver requires config['image']")
        cmd = []
        if cfg.get("command"):
            cmd = [cfg["command"]] + list(cfg.get("args", []))

        if cfg.get("force_pull") or cfg.get("pull", True):
            try:
                self.api.pull(image)
            except DriverError:
                # image may exist locally; create() is the authority
                pass

        res = getattr(task, "resources", None)
        host_config: dict = {
            # alloc/task dir visible in-container (docker/driver.go mounts)
            "Binds": [f"{task_dir}:/alloc"],
        }
        if res is not None:
            if getattr(res, "memory_mb", 0):
                host_config["Memory"] = int(res.memory_mb) * 1024 * 1024
            if getattr(res, "cpu", 0):
                # MHz ask → proportional NanoCpus share (1000 MHz ≈ 1 cpu)
                host_config["NanoCpus"] = int(res.cpu * 1e6)
        spec = {
            "Image": image,
            "Cmd": cmd or None,
            "Env": [f"{k}={v}" for k, v in (env or {}).items()],
            "WorkingDir": "/alloc",
            "HostConfig": host_config,
            "Labels": {
                "com.nomad-tpu.task": task.name,
            },
        }
        cid = self.api.create(spec, name=f"nomad-{task.name}-{os.getpid()}-{int(time.time()*1000) % 1_000_000}")
        try:
            self.api.start(cid)
        except DriverError:
            try:
                self.api.remove(cid)
            except DriverError:
                pass
            raise
        h = TaskHandle(id=cid, driver=self.name)
        h.meta["image"] = image
        h.meta["task_dir"] = task_dir
        h.meta["task_name"] = task.name
        return h

    def wait(self, handle, timeout=None):
        code = self.api.wait(handle.id, timeout=timeout)
        if code is None:
            return None
        handle.state = TASK_STATE_DEAD
        handle.exit_code = code
        handle.completed_at = time.time()
        self._drain_logs(handle)
        return code

    def stop(self, handle, kill_timeout=5.0):
        try:
            self.api.stop(handle.id, grace_s=kill_timeout)
        except DriverError:
            pass  # already stopped/removed
        st = self.api.inspect(handle.id)
        if st is not None:
            code = (st.get("State") or {}).get("ExitCode")
            handle.exit_code = int(code) if code is not None else None
            handle.state = TASK_STATE_DEAD
            handle.completed_at = time.time()
            self._drain_logs(handle)
        try:
            self.api.remove(handle.id)
        except DriverError:
            pass

    def inspect(self, handle: TaskHandle) -> TaskHandle:
        st = self.api.inspect(handle.id)
        if st is None:
            handle.state = TASK_STATE_DEAD
            return handle
        state = st.get("State") or {}
        if state.get("Running"):
            handle.state = TASK_STATE_RUNNING
        else:
            handle.state = TASK_STATE_DEAD
            code = state.get("ExitCode")
            handle.exit_code = int(code) if code is not None else None
        return handle

    def recover(self, handle: TaskHandle) -> bool:
        """Reattach by container id (docker/handle.go RecoverTask): the
        daemon outlives both the plugin subprocess and the client, so a
        restart re-binds to the same container — and an exit that
        happened while we were away still yields its REAL code."""
        st = self.api.inspect(handle.id)
        if st is None:
            return False
        state = st.get("State") or {}
        if state.get("Running"):
            handle.state = TASK_STATE_RUNNING
            handle.meta["recovered"] = True
            return True
        # exited while the client was down: report the true exit status
        code = state.get("ExitCode")
        handle.exit_code = int(code) if code is not None else None
        handle.state = TASK_STATE_DEAD
        handle.meta["recovered"] = True
        self._drain_logs(handle)
        return True

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _demux_log_stream(data: bytes) -> bytes:
        """Strip the Engine's stdcopy multiplexing, if present.

        A non-TTY container's log endpoint returns stdcopy frames:
        ``[stream_type, 0, 0, 0, len_be32][payload]``. Writing that raw
        into the task's log files would interleave 8-byte binary headers
        with the output. A TTY container (and some daemons) return raw
        bytes — so only strip when the ENTIRE buffer walks cleanly as
        frames (raw output that happens to start with 0x00-0x02 is
        astronomically unlikely to frame-walk to an exact end)."""
        out = []
        i = 0
        n = len(data)
        while i + 8 <= n:
            if data[i] not in (0, 1, 2) or data[i + 1 : i + 4] != b"\x00\x00\x00":
                return data  # not framed
            ln = int.from_bytes(data[i + 4 : i + 8], "big")
            if i + 8 + ln > n:
                return data  # truncated/not framed
            out.append(data[i + 8 : i + 8 + ln])
            i += 8 + ln
        if i != n:
            return data
        return b"".join(out)

    def _drain_logs(self, handle: TaskHandle) -> None:
        """Copy daemon-held logs into the task dir so fs/logs endpoints
        serve container output like any exec task's."""
        task_dir = handle.meta.get("task_dir")
        task_name = handle.meta.get("task_name")
        if not task_dir or not task_name or not os.path.isdir(task_dir):
            return
        for is_err, suffix in ((False, "stdout"), (True, "stderr")):
            try:
                data = self.api.logs(handle.id, stderr=is_err)
            except (DriverError, OSError):
                continue
            if not data:
                continue
            data = self._demux_log_stream(data)
            path = os.path.join(task_dir, f"{task_name}.{suffix}")
            try:
                with open(path, "ab") as f:
                    f.write(data)
            except OSError:
                pass
