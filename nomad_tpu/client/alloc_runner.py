"""AllocRunner — per-allocation supervisor.

Reference: client/allocrunner/alloc_runner.go (:36-120): set up the alloc
dir, run one TaskRunner per task (leader/sidecar ordering via the task
hook coordinator is honored in its simplest form: all mains in parallel),
aggregate task states into the alloc's client status, and report changes
up to the client for batched server sync.

Client status derivation mirrors getClientStatus (alloc_runner.go):
any task failed ⇒ failed; any running ⇒ running; all dead+ok ⇒ complete.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
from typing import Callable, Optional

from ..structs import (
    ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_PENDING,
    ALLOC_CLIENT_RUNNING,
    Allocation,
)
from .task_runner import TaskRunner, TaskState

log = logging.getLogger("nomad_tpu.alloc_runner")


class AllocRunner:
    def __init__(
        self,
        alloc: Allocation,
        drivers: dict,
        data_dir: str,
        on_update: Optional[Callable[[Allocation, str, dict], None]] = None,
        restored_handles: Optional[dict] = None,
        on_handle: Optional[Callable] = None,
        prev_watcher: Optional[Callable] = None,
        device_plugins: Optional[dict] = None,
        device_group_owner: Optional[dict] = None,
        csi_plugins: Optional[dict] = None,
        csi_volume_resolver: Optional[Callable] = None,
    ):
        self.alloc = alloc
        self.drivers = drivers
        self.alloc_dir = os.path.join(data_dir, "allocs", alloc.id)
        self.on_update = on_update
        # allocwatcher seam (client/allocwatcher): blocks until the
        # previous allocation stops and returns its alloc dir for
        # ephemeral-disk migration (None = remote/unknown previous)
        self.prev_watcher = prev_watcher
        # task_name → recovered TaskHandle (client restart re-attach)
        self.restored_handles = restored_handles or {}
        self.on_handle = on_handle
        # device-plugin clients (name → DevicePluginClient) for Reserve,
        # plus the (vendor, type, name) → plugin-name ownership map
        self.device_plugins = device_plugins or {}
        self.device_group_owner = device_group_owner or {}
        # CSI plugin clients (name → CSIPluginClient) for the
        # stage/publish lifecycle; published (plugin, volume_id, target)
        # triples recorded for teardown
        self.csi_plugins = csi_plugins or {}
        # volume_id -> (resolved_id, plugin_id) via the server (routing +
        # per_alloc fallback); None in plugin-less/standalone setups
        self.csi_volume_resolver = csi_volume_resolver
        self._published_volumes: list[tuple] = []
        self.task_runners: dict[str, TaskRunner] = {}
        self.task_states: dict[str, TaskState] = {}
        self._lock = threading.Lock()
        self._destroyed = False

    # -- lifecycle ---------------------------------------------------------
    def run(self) -> None:
        job = self.alloc.job
        tg = job.lookup_task_group(self.alloc.task_group) if job else None
        if tg is None:
            self._report(ALLOC_CLIENT_FAILED, "unknown task group")
            return
        os.makedirs(self.alloc_dir, exist_ok=True)
        self._migrate_previous(tg)
        env = {
            "NOMAD_ALLOC_ID": self.alloc.id,
            "NOMAD_ALLOC_NAME": self.alloc.name,
            "NOMAD_ALLOC_INDEX": str(self.alloc.index()),
            "NOMAD_ALLOC_DIR": os.path.join(self.alloc_dir, "shared"),
            "NOMAD_JOB_NAME": job.name if job else "",
            "NOMAD_GROUP_NAME": tg.name,
        }
        os.makedirs(env["NOMAD_ALLOC_DIR"], exist_ok=True)
        try:
            env.update(self._reserve_devices())
            env.update(self._publish_csi_volumes(tg))
        except RuntimeError as e:
            log.warning("alloc %s: %s", self.alloc.id[:8], e)
            self._report(ALLOC_CLIENT_FAILED, str(e))
            return
        for task in tg.tasks:
            driver = self.drivers.get(task.driver)
            if driver is None:
                self._report(
                    ALLOC_CLIENT_FAILED, f"driver {task.driver!r} not found"
                )
                return
            tr = TaskRunner(
                task=task,
                driver=driver,
                task_dir=os.path.join(self.alloc_dir, task.name),
                env=env,
                restart_policy=tg.restart_policy,
                on_state_change=self._on_task_state,
                attach_handle=self.restored_handles.get(task.name),
                on_handle=(
                    (lambda name, h: self.on_handle(self.alloc.id, name, h))
                    if self.on_handle is not None else None
                ),
            )
            self.task_runners[task.name] = tr
        for tr in self.task_runners.values():
            tr.start()
        self._report(ALLOC_CLIENT_RUNNING, "tasks are running")

    def _reserve_devices(self) -> dict:
        """Resolve the alloc's scheduled device instances through the
        device plugins (device.proto Reserve): each AllocatedDeviceResource
        routes to the plugin that OWNS its (vendor, type, name) group, and
        the reservation's env mutations flow into every task's environment
        (the reference mutates the container config; env is this build's
        common denominator across drivers). A failed reservation FAILS the
        alloc — starting without device isolation would let the task use
        instances reserved by other allocs."""
        assigned = getattr(self.alloc, "allocated_devices", None) or []
        if not assigned:
            return {}
        if not self.device_plugins:
            # scheduled device instances with no plugin to reserve them
            # (e.g. the plugin failed fingerprint on restart): starting
            # unconfined would let the task use other allocs' instances
            raise RuntimeError(
                "alloc has allocated devices but no device plugin is "
                "available to reserve them"
            )
        envs: dict = {}
        for ad in assigned:
            ids = list(getattr(ad, "device_ids", None) or [])
            if not ids:
                continue
            owner = self.device_group_owner.get(
                (ad.vendor, ad.type, ad.name)
            )
            dp = self.device_plugins.get(owner) if owner else None
            if dp is None:
                raise RuntimeError(
                    f"no device plugin owns group "
                    f"{ad.vendor}/{ad.type}/{ad.name}"
                )
            try:
                res = dp.reserve(ids)
            except Exception as e:
                raise RuntimeError(
                    f"device reserve failed for "
                    f"{ad.vendor}/{ad.type}/{ad.name}: {e}"
                ) from e
            envs.update(res.get("envs") or {})
        return envs

    def _publish_csi_volumes(self, tg) -> dict:
        """Stage + publish each CSI volume request through the plugin
        that OWNS it (csimanager/volume.go's NodeStage→NodePublish half;
        the server's claim lifecycle already gated scheduling). The
        volume resolves through the server (``csi_volume_info``) so the
        published id and the claimed id agree — including the per_alloc
        fallback to the base source the scheduler and applier use. The
        published path is exposed at <alloc_dir>/volumes/<name> and as
        NOMAD_VOLUME_<NAME> in every task's env. Failures FAIL the alloc
        (with staged-but-unpublished volumes unstaged and earlier
        publishes torn down) — running without a declared volume is the
        reference's failure mode too."""
        volumes = getattr(tg, "volumes", None) or {}
        csi_reqs = {
            name: req
            for name, req in volumes.items()
            if getattr(req, "type", "") == "csi"
        }
        if not csi_reqs:
            return {}
        if not self.csi_plugins:
            raise RuntimeError(
                "alloc requests CSI volumes but no CSI plugin is available"
            )
        envs: dict = {}
        staging_root = os.path.join(self.alloc_dir, "csi-staging")
        try:
            for name, req in csi_reqs.items():
                vol_id = req.source
                if getattr(req, "per_alloc", False):
                    vol_id = f"{req.source}[{self.alloc.index()}]"
                plugin_id = None
                if self.csi_volume_resolver is not None:
                    info = self.csi_volume_resolver(vol_id)
                    if info is not None:
                        # server-resolved id (per_alloc falls back to the
                        # base source exactly like scheduling/apply did)
                        vol_id, plugin_id = info
                if plugin_id is not None:
                    plugin = self.csi_plugins.get(plugin_id)
                    if plugin is None:
                        raise RuntimeError(
                            f"volume {vol_id} needs CSI plugin "
                            f"{plugin_id!r}, which this node does not run"
                        )
                elif len(self.csi_plugins) == 1:
                    plugin = next(iter(self.csi_plugins.values()))
                else:
                    raise RuntimeError(
                        f"cannot route volume {vol_id}: no resolver and "
                        f"{len(self.csi_plugins)} plugins configured"
                    )
                target = os.path.join(self.alloc_dir, "volumes", name)
                staged = False
                try:
                    plugin.node_stage(
                        vol_id, os.path.join(staging_root, name)
                    )
                    staged = True
                    plugin.node_publish(
                        vol_id, target,
                        read_only=getattr(req, "read_only", False),
                    )
                except Exception as e:
                    if staged:
                        # stage succeeded, publish failed: a real driver
                        # would leak the staged mount otherwise
                        try:
                            plugin.node_unstage(vol_id)
                        except Exception:  # noqa: BLE001
                            pass
                    raise RuntimeError(
                        f"csi volume {name} ({vol_id}): {e}"
                    ) from e
                self._published_volumes.append((plugin, vol_id, target))
                envs[
                    f"NOMAD_VOLUME_{name.upper().replace('-', '_')}"
                ] = target
        except RuntimeError:
            # tear down whatever already published for this alloc — a
            # failed alloc must not hold volumes mounted
            self._unpublish_csi_volumes()
            raise
        return envs

    def _unpublish_csi_volumes(self) -> None:
        for plugin, vol_id, target in self._published_volumes:
            # separate trys: a failed unpublish must not skip the
            # unstage (that would leak the staged mount)
            try:
                plugin.node_unpublish(vol_id, target)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                log.warning(
                    "csi unpublish failed for %s", vol_id, exc_info=True
                )
            try:
                plugin.node_unstage(vol_id)
            except Exception:  # noqa: BLE001
                log.warning(
                    "csi unstage failed for %s", vol_id, exc_info=True
                )
        self._published_volumes = []

    def _migrate_previous(self, tg) -> None:
        """Previous-alloc data migration (client/allocwatcher +
        migrate_hook): with ephemeral_disk.migrate/sticky, wait for the
        previous allocation to stop, then carry its shared dir into this
        alloc. The reference streams remote dirs over the node API; this
        build migrates same-node dirs and degrades to wait-only for
        remote previous allocs."""
        prev = self.alloc.previous_allocation
        ed = getattr(tg, "ephemeral_disk", None)
        if not prev or self.prev_watcher is None or ed is None:
            return
        if not (ed.migrate or ed.sticky):
            return
        src_dir = self.prev_watcher(prev)
        if not src_dir:
            return
        src_shared = os.path.join(src_dir, "shared")
        dst_shared = os.path.join(self.alloc_dir, "shared")
        try:
            if os.path.isdir(src_shared):
                shutil.copytree(src_shared, dst_shared, dirs_exist_ok=True)
        except (OSError, shutil.Error):
            # the previous dir can be GC'd/destroyed concurrently — a
            # failed migration degrades to a fresh disk, never a stuck
            # alloc (run() has no other guard above the task loop)
            pass

    def wait(self, timeout: Optional[float] = None) -> None:
        for tr in self.task_runners.values():
            tr.join(timeout=timeout)

    def stop(self) -> None:
        """Graceful stop (desired_status=stop): leader-last kill order."""
        for tr in self.task_runners.values():
            tr.kill()
        self._unpublish_csi_volumes()
        self._report(self.client_status(), "alloc stopped")

    def destroy(self) -> None:
        """GC: stop + remove the alloc dir (client/gc.go)."""
        self.stop()
        self._destroyed = True
        shutil.rmtree(self.alloc_dir, ignore_errors=True)

    # -- status ------------------------------------------------------------
    def _on_task_state(self, name: str, state: TaskState) -> None:
        with self._lock:
            self.task_states[name] = state
        self._report(self.client_status(), "")

    def client_status(self) -> str:
        states = list(self.task_states.values())
        if not states:
            return ALLOC_CLIENT_PENDING
        if any(s.failed for s in states):
            return ALLOC_CLIENT_FAILED
        if any(s.state == "running" for s in states):
            return ALLOC_CLIENT_RUNNING
        if all(s.state == "dead" for s in states):
            return ALLOC_CLIENT_COMPLETE
        return ALLOC_CLIENT_PENDING

    def is_terminal(self) -> bool:
        states = list(self.task_states.values())
        return bool(states) and all(s.state == "dead" for s in states)

    def _report(self, status: str, desc: str) -> None:
        if self.on_update is not None:
            self.on_update(self.alloc, status, dict(self.task_states))
