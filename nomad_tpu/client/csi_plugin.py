"""Out-of-process CSI plugin contract — the plugins/csi analog.

Reference: plugins/csi/ (client.go: a gRPC client to an external CSI
driver's controller/node services over its unix socket; the volume
lifecycle is ControllerPublish → NodeStage → NodePublish and the reverse
on teardown, csimanager/volume.go). The server side of this build
already models volumes/claims/plugins and re-verifies claims in the plan
applier; this module adds the CLIENT-side external contract: a CSI
plugin is a separate process speaking CSI-shaped calls over the
framework's NDJSON stdio transport (uniform with driver and device
plugins — no protobuf toolchain), and the alloc runner drives the
stage/publish lifecycle around task execution.

Methods (CSI spec names, trimmed to the implemented semantics):
  probe                         → {"ready": bool}
  controller_publish            {volume_id, node_id}        → {}
  controller_unpublish          {volume_id, node_id}        → {}
  node_stage                    {volume_id, staging_path}   → {}
  node_unstage                  {volume_id}                 → {}
  node_publish                  {volume_id, target_path,
                                 read_only}                 → {}
  node_unpublish                {volume_id, target_path}    → {}

``HostPathCSIPlugin`` is the bundled reference implementation (the
csi-driver-host-path analog): volumes are directories under a root, and
publish materializes them at the target path — real enough to carry
data between allocs in tests and single-node deployments.
"""

from __future__ import annotations

import os
import shutil
import sys
from typing import Optional

from .stdio_plugin import StdioPluginClient, serve_stdio_plugin

CSI_PLUGIN_MAGIC = "NOMAD_TPU_CSI_V1"
CSI_PROTO_VERSION = 1


class CSIPlugin:
    """Plugin-side base."""

    name = "csi"

    def probe(self) -> dict:
        return {"ready": True}

    def controller_publish(self, volume_id: str, node_id: str) -> dict:
        return {}

    def controller_unpublish(self, volume_id: str, node_id: str) -> dict:
        return {}

    def node_stage(self, volume_id: str, staging_path: str) -> dict:
        return {}

    def node_unstage(self, volume_id: str) -> dict:
        return {}

    def node_publish(
        self, volume_id: str, target_path: str, read_only: bool
    ) -> dict:
        return {}

    def node_unpublish(self, volume_id: str, target_path: str) -> dict:
        return {}


class HostPathCSIPlugin(CSIPlugin):
    """csi-driver-host-path analog: volume_id ↔ a directory under
    ``root`` (env NOMAD_CSI_HOSTPATH_ROOT, default /tmp/nomad-csi).
    Publish materializes the volume at target_path via symlink, so data
    written by one alloc is visible to the next — the property the CSI
    lifecycle exists to provide.

    Known limitation: ``read_only`` is accepted but NOT enforced — the
    symlink is writable either way (a faithful read-only publish needs a
    bind mount or an overlay, which this reference plugin deliberately
    avoids). The server-side claim accounting still enforces access-mode
    admission; a misbehaving "reader" task can violate it here. Real CSI
    drivers enforce read-only at the mount."""

    name = "hostpath"

    def __init__(self, root: Optional[str] = None):
        self.root = root or os.environ.get(
            "NOMAD_CSI_HOSTPATH_ROOT", "/tmp/nomad-csi"
        )
        self._staged: set[str] = set()

    def _vol_dir(self, volume_id: str) -> str:
        safe = volume_id.replace("/", "_")
        return os.path.join(self.root, safe)

    def node_stage(self, volume_id: str, staging_path: str) -> dict:
        os.makedirs(self._vol_dir(volume_id), exist_ok=True)
        self._staged.add(volume_id)
        return {}

    def node_unstage(self, volume_id: str) -> dict:
        self._staged.discard(volume_id)
        return {}

    def node_publish(
        self, volume_id: str, target_path: str, read_only: bool
    ) -> dict:
        if volume_id not in self._staged:
            raise RuntimeError(f"volume {volume_id} not staged")
        vol = self._vol_dir(volume_id)
        os.makedirs(os.path.dirname(target_path), exist_ok=True)
        if os.path.islink(target_path):
            os.unlink(target_path)
        elif os.path.isdir(target_path):
            shutil.rmtree(target_path)
        os.symlink(vol, target_path)
        return {}

    def node_unpublish(self, volume_id: str, target_path: str) -> dict:
        if os.path.islink(target_path):
            os.unlink(target_path)
        return {}


BUILTIN_CSI_PLUGINS = {"hostpath": HostPathCSIPlugin}


# -- plugin (server) side ----------------------------------------------------


def serve_csi_plugin(plugin: CSIPlugin, stdin=None, stdout=None) -> None:
    serve_stdio_plugin(
        CSI_PLUGIN_MAGIC,
        CSI_PROTO_VERSION,
        plugin.name,
        {
            "probe": lambda p: plugin.probe(),
            "controller_publish": lambda p: plugin.controller_publish(
                p["volume_id"], p["node_id"]
            ),
            "controller_unpublish": lambda p: plugin.controller_unpublish(
                p["volume_id"], p["node_id"]
            ),
            "node_stage": lambda p: plugin.node_stage(
                p["volume_id"], p["staging_path"]
            ),
            "node_unstage": lambda p: plugin.node_unstage(
                p["volume_id"]
            ),
            "node_publish": lambda p: plugin.node_publish(
                p["volume_id"], p["target_path"],
                bool(p.get("read_only")),
            ),
            "node_unpublish": lambda p: plugin.node_unpublish(
                p["volume_id"], p["target_path"]
            ),
        },
        stdin=stdin,
        stdout=stdout,
    )


# -- host (client) side ------------------------------------------------------


class CSIPluginClient(StdioPluginClient):
    """Spawns and drives one CSI plugin subprocess (csimanager's
    instance-manager role)."""

    MAGIC = CSI_PLUGIN_MAGIC
    VERSION = CSI_PROTO_VERSION

    def default_argv(self, name: str) -> list[str]:
        return [
            sys.executable, "-m", "nomad_tpu.client.csi_plugin", name,
        ]

    # -- contract ----------------------------------------------------------
    def probe(self) -> bool:
        try:
            return bool((self._call("probe") or {}).get("ready"))
        except (RuntimeError, OSError):
            return False

    def node_stage(self, volume_id: str, staging_path: str) -> None:
        self._call(
            "node_stage",
            {"volume_id": volume_id, "staging_path": staging_path},
        )

    def node_unstage(self, volume_id: str) -> None:
        self._call("node_unstage", {"volume_id": volume_id})

    def node_publish(
        self, volume_id: str, target_path: str, read_only: bool = False
    ) -> None:
        self._call(
            "node_publish",
            {
                "volume_id": volume_id,
                "target_path": target_path,
                "read_only": read_only,
            },
        )

    def node_unpublish(self, volume_id: str, target_path: str) -> None:
        self._call(
            "node_unpublish",
            {"volume_id": volume_id, "target_path": target_path},
        )


def _main() -> None:
    name = sys.argv[1] if len(sys.argv) > 1 else "hostpath"
    factory = BUILTIN_CSI_PLUGINS.get(name)
    if factory is None:
        print(f"unknown csi plugin {name!r}", file=sys.stderr)
        raise SystemExit(2)
    serve_csi_plugin(factory())


if __name__ == "__main__":
    _main()
