"""Client — the node agent kernel.

Reference: client/client.go (:167 Client): fingerprint + register the
node, heartbeat on the server-assigned TTL, watch allocations (blocking
pull keyed by state index, client.go watchAllocations), reconcile local
AllocRunners against desired state (run new, stop stopped, GC removed),
and sync alloc status back in batches (200 ms batching, client.go:99-101).

The server link is the ``ServerRPC`` seam — in-process for the dev agent,
msgpack/gRPC transport later without touching this file.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional, Protocol

from ..structs import (
    ALLOC_DESIRED_RUN,
    ALLOC_DESIRED_STOP,
    Allocation,
    Node,
)
from .alloc_runner import AllocRunner
from .drivers import builtin_drivers
from .fingerprint import fingerprint_node
from .state import ClientStateDB

log = logging.getLogger("nomad_tpu.client")

ALLOC_SYNC_INTERVAL = 0.2  # client.go:99-101 allocSyncIntv

# terminal alloc dirs retained before the GC sweep reclaims the oldest
# (client/gc.go MaxAllocs-style bound; disk-usage triggers reduce to a
# count bound in this build — the dirs are tiny without artifacts)
GC_MAX_TERMINAL_ALLOCS = 50
GC_INTERVAL = 1.0


class ServerRPC(Protocol):
    def register_node(self, node: Node) -> None: ...

    def heartbeat(self, node_id: str) -> float: ...  # returns TTL seconds

    def pull_allocs(
        self, node_id: str, min_index: int, timeout: float
    ) -> tuple[list[Allocation], int]: ...

    def update_allocs(self, updates: list[Allocation]) -> None: ...


class Client:
    def __init__(
        self,
        rpc: ServerRPC,
        data_dir: str,
        node: Optional[Node] = None,
        heartbeat_interval: Optional[float] = None,
        host_volumes: Optional[dict] = None,
        serve_endpoints: bool = True,
        driver_mode: str = "inprocess",
        device_plugins: Optional[list[str]] = None,
        csi_plugins: Optional[list[str]] = None,
    ):
        self.rpc = rpc
        self.data_dir = data_dir
        self._serve_endpoints = serve_endpoints
        self.endpoints = None
        self.state_db = ClientStateDB(data_dir)
        if driver_mode == "plugin":
            # out-of-process driver plugins (driver.proto contract over
            # stdio NDJSON — client/plugin.py); tasks and their reattach
            # handles survive plugin AND client restarts
            from .plugin import plugin_drivers

            self.drivers = plugin_drivers()
        else:
            self.drivers = builtin_drivers()
        self.node = fingerprint_node(node, data_dir=data_dir, drivers=self.drivers)
        # out-of-process device plugins (device.proto analog — see
        # client/device_plugin.py): fingerprinted groups surface on the
        # node for the scheduler's DeviceChecker/allocator; reservations
        # are resolved at task start into env/mount mutations
        self.device_plugins: dict[str, object] = {}
        # (vendor, type, name) → plugin name: Reserve must route each
        # allocated device group to the plugin that OWNS it (sending ids
        # to every plugin would let e.g. the jax plugin misparse a fake
        # device id into a TPU ordinal pin)
        self.device_group_owner: dict[tuple, str] = {}
        for dp_name in device_plugins or []:
            from .device_plugin import DevicePluginClient

            dp = DevicePluginClient(dp_name)
            try:
                groups = dp.fingerprint()
            except Exception:
                log.warning("device plugin %s failed", dp_name, exc_info=True)
                continue
            self.device_plugins[dp_name] = dp
            if groups:
                self.node.node_resources.devices.extend(groups)
                for g in groups:
                    self.device_group_owner[
                        (g.vendor, g.type, g.name)
                    ] = dp_name
                self.node.attributes[f"device.{dp_name}"] = str(
                    sum(len(g.instances) for g in groups)
                )
                self.node.compute_class()
        # out-of-process CSI plugins (plugins/csi analog — see
        # client/csi_plugin.py): the alloc runner drives NodeStage/
        # NodePublish through them around task execution
        self.csi_plugins: dict[str, object] = {}
        for cp_name in csi_plugins or []:
            from .csi_plugin import CSIPluginClient

            cp = CSIPluginClient(cp_name)
            if cp.probe():
                from ..structs.volumes import CSINodeInfo

                self.csi_plugins[cp_name] = cp
                # the structured node surface the scheduler's
                # CSIVolumeChecker reads (Node.CSINodePlugins)
                self.node.csi_node_plugins[cp_name] = CSINodeInfo(
                    plugin_id=cp_name, healthy=True
                )
                self.node.attributes[f"csi.{cp_name}"] = "1"
                self.node.compute_class()
            else:
                log.warning("csi plugin %s failed probe", cp_name)
                cp.close()
        if host_volumes:
            # client config host_volume blocks surface on the node for the
            # HostVolumeChecker (structs.ClientHostVolumeConfig)
            self.node.host_volumes.update(host_volumes)
            self.node.compute_class()
        self.heartbeat_interval = heartbeat_interval
        self.runners: dict[str, AllocRunner] = {}
        self._pending_updates: dict[str, Allocation] = {}
        # alloc id → client-side health verdict (allochealth tracker);
        # attached to every subsequent sync so a later task-state update
        # can't erase the verdict in flight
        self._health_verdicts: dict[str, bool] = {}
        self._health_trackers: dict[str, object] = {}
        self._lock = threading.Lock()
        self._logmon_lock = threading.Lock()  # serializes log rotation
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._last_index = 0
        # heartbeatstop (client/heartbeatstop.go:11-40): last server
        # contact; allocs with stop_after_client_disconnect stop when the
        # client has been out of contact longer than their threshold
        self._last_ok_heartbeat = time.time()
        self._heartbeat_stopped: set[str] = set()
        self.gc_max_terminal_allocs = GC_MAX_TERMINAL_ALLOCS
        # terminal alloc ids in completion order (oldest first) for GC
        self._terminal_order: list[str] = []
        # alloc ids whose TERMINAL status the server has acknowledged —
        # only these are GC-eligible (destroying durable state before the
        # ack would let a post-partition reconcile re-run the alloc)
        self._acked_terminal: set[str] = set()
        # telemetry.publish_allocation_metrics (command/agent/config.go
        # Telemetry): per-alloc client-status counters on state changes
        self.publish_allocation_metrics = False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.node.status = "ready"
        if self._serve_endpoints:
            from .endpoints import ATTR_RPC_ADDR, ClientEndpoints

            self.endpoints = ClientEndpoints(self)
            addr = self.endpoints.start()
            # advertised BEFORE registration so fs/logs proxying can reach
            # this node (client/fs_endpoint.go reachability)
            self.node.attributes[ATTR_RPC_ADDR] = addr
        self._restore()
        self.rpc.register_node(self.node)
        for fn, name in (
            (self._heartbeat_loop, "heartbeat"),
            (self._watch_allocations, "alloc-watch"),
            (self._sync_loop, "alloc-sync"),
            (self._gc_loop, "gc"),
            (self._driver_health_loop, "driver-health"),
        ):
            t = threading.Thread(target=fn, name=f"client-{name}", daemon=True)
            t.start()
            self._threads.append(t)

    def shutdown(self, halt_tasks: bool = True) -> None:
        """``halt_tasks=False`` leaves task processes running for a
        restart to re-attach to (the client-restart upgrade path the
        persistent state exists for)."""
        self._stop.set()
        if halt_tasks:
            for r in list(self.runners.values()):
                r.stop()
        for t in self._threads:
            t.join(timeout=2)
        if self.endpoints is not None:
            self.endpoints.stop()
        for d in self.drivers.values():
            close = getattr(d, "close", None)
            if close is not None:
                close()
        for dp in list(self.device_plugins.values()) + list(
            self.csi_plugins.values()
        ):
            try:
                dp.close()
            except Exception:  # noqa: BLE001 — shutdown is best-effort
                pass
        self.state_db.close()

    # -- restore (client/state StateDB; task_runner.go:488-519) -----------
    def _restore(self) -> None:
        for alloc in self.state_db.allocs():
            if alloc.terminal_status() or alloc.desired_status != ALLOC_DESIRED_RUN:
                self.state_db.delete_alloc(alloc.id)
                continue
            handles = self.state_db.handles_for(alloc.id)
            recovered = {}
            for name, h in handles.items():
                driver = self.drivers.get(h.driver)
                if driver is not None and driver.recover(h):
                    recovered[name] = h
                    log.info(
                        "restored task %s/%s (pid %s)", alloc.id[:8], name, h.pid
                    )
            runner = AllocRunner(
                alloc, self.drivers, self.data_dir,
                on_update=self._on_alloc_update,
                restored_handles=recovered,
                on_handle=self.state_db.put_handle,
                device_plugins=self.device_plugins,
                device_group_owner=self.device_group_owner,
                csi_plugins=self.csi_plugins,
                csi_volume_resolver=self._csi_volume_resolver,
            )
            with self._lock:
                self.runners[alloc.id] = runner
            threading.Thread(
                target=runner.run, name=f"alloc-{alloc.id[:8]}", daemon=True
            ).start()
            self._maybe_track_health(runner)

    def _csi_volume_resolver(self, volume_id: str):
        """Server-side volume resolution for CSI publish routing (the
        Node->CSIVolume.Get hop). None when the transport lacks the call
        or the volume is unknown; a TRANSIENT RPC failure RAISES — the
        alloc must fail and retry rather than silently publish an
        unresolved id (which a hostpath-style plugin would materialize
        as a fresh empty volume)."""
        fn = getattr(self.rpc, "csi_volume_info", None)
        if fn is None:
            return None
        try:
            return fn(volume_id)
        except Exception as e:  # noqa: BLE001
            raise RuntimeError(
                f"csi volume resolution failed for {volume_id}: {e}"
            ) from e

    # -- heartbeats --------------------------------------------------------
    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            try:
                ttl = self.rpc.heartbeat(self.node.id)
                self._last_ok_heartbeat = time.time()
                self._heartbeat_stopped.clear()
            except Exception:
                log.warning("heartbeat failed", exc_info=True)
                ttl = 1.0
                self._check_heartbeat_stop()
            interval = self.heartbeat_interval or max(ttl / 2.0, 0.05)
            self._stop.wait(interval)

    def _check_heartbeat_stop(self) -> None:
        """heartbeatstop (client/heartbeatstop.go:11-40): when server
        contact has been lost longer than a group's
        ``stop_after_client_disconnect``, stop its allocs locally — the
        server has already considered them lost and replaced them, so
        letting them run risks a split-brain double-run."""
        elapsed = time.time() - self._last_ok_heartbeat
        with self._lock:
            runners = list(self.runners.items())
        for alloc_id, runner in runners:
            if alloc_id in self._heartbeat_stopped or runner._destroyed:
                continue
            a = runner.alloc
            tg = (
                a.job.lookup_task_group(a.task_group)
                if a.job is not None
                else None
            )
            threshold = (
                tg.stop_after_client_disconnect_s if tg is not None else None
            )
            if threshold is not None and elapsed >= threshold:
                log.info(
                    "heartbeatstop: stopping alloc %s after %.1fs without "
                    "server contact (threshold %.1fs)",
                    alloc_id[:8], elapsed, threshold,
                )
                self._heartbeat_stopped.add(alloc_id)
                runner.stop()

    # -- driver health supervision (client/pluginmanager/drivermanager) ----
    DRIVER_HEALTH_INTERVAL = 5.0

    def _driver_health_loop(self) -> None:
        """The driver-manager loop: periodically re-fingerprint every
        driver and push node updates when health flips, so the scheduler
        stops placing on drivers that died (and resumes when a plugin
        recovers — PluginDriverClient respawns its subprocess lazily, so
        a crashed plugin heals through this same probe)."""
        push_pending = False
        while not self._stop.is_set():
            self._stop.wait(self.DRIVER_HEALTH_INTERVAL)
            if self._stop.is_set():
                return
            changed = False
            for name, drv in self.drivers.items():
                try:
                    healthy = bool(drv.fingerprint())
                except Exception:
                    healthy = False
                if self.node.drivers.get(name) != healthy:
                    self.node.drivers[name] = healthy
                    self.node.attributes[f"driver.{name}"] = (
                        "1" if healthy else "0"
                    )
                    changed = True
                    log.info(
                        "driver %s is now %s",
                        name,
                        "healthy" if healthy else "unhealthy",
                    )
            if changed or push_pending:
                # push_pending: a failed push is retried next tick even
                # though the local state already reflects the change
                self.node.compute_class()
                try:
                    self.rpc.register_node(self.node)
                    push_pending = False
                except Exception:
                    push_pending = True
                    log.exception("node update after driver change failed")

    # -- terminal-alloc GC (client/gc.go) ----------------------------------
    def _gc_loop(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(GC_INTERVAL)
            try:
                self.gc_sweep()
                self.logmon_sweep()
            except Exception:
                log.exception("alloc GC sweep failed")

    def logmon_sweep(self) -> int:
        """Rotate oversized task logs (client/logmon's retention role —
        LogConfig MaxFiles × MaxFileSizeMB, copy-truncate). Serialized:
        two concurrent sweepers would re-rotate a just-truncated file and
        clobber the archived copy with an empty one."""
        from .logmon import sweep_alloc

        with self._lock:
            runners = list(self.runners.values())
        with self._logmon_lock:
            return sum(sweep_alloc(r) for r in runners if not r._destroyed)

    def gc_sweep(self) -> None:
        """Reclaim the oldest terminal alloc dirs beyond the retention
        bound (client/gc.go: disk-driven destroy of terminal allocs; this
        build bounds by count). Allocs whose final status is still
        awaiting server sync are NOT reclaimed — destroying the runner
        and its durable state before the server learns the alloc
        finished would let a post-partition reconcile re-run it."""
        with self._lock:
            self._terminal_order = [
                aid for aid in self._terminal_order if aid in self.runners
            ]
            for alloc_id, runner in self.runners.items():
                if runner.is_terminal() and alloc_id not in self._terminal_order:
                    self._terminal_order.append(alloc_id)
            eligible = [
                aid
                for aid in self._terminal_order
                if aid in self._acked_terminal
            ]
            excess = len(eligible) - self.gc_max_terminal_allocs
            victims = eligible[: max(excess, 0)]
        for alloc_id in victims:
            with self._lock:
                runner = self.runners.pop(alloc_id, None)
                if alloc_id in self._terminal_order:
                    self._terminal_order.remove(alloc_id)
            self._acked_terminal.discard(alloc_id)  # bound the ack set
            self._drop_health_tracking(alloc_id)
            if runner is not None:
                runner.destroy()
            self.state_db.delete_alloc(alloc_id)
            log.info("gc: reclaimed terminal alloc %s", alloc_id[:8])

    # -- alloc pull + reconcile (client.go watchAllocations) ---------------
    def _watch_allocations(self) -> None:
        while not self._stop.is_set():
            try:
                allocs, index = self.rpc.pull_allocs(
                    self.node.id, self._last_index, timeout=1.0
                )
            except Exception:
                log.exception("alloc pull failed")
                self._stop.wait(1.0)
                continue
            if index <= self._last_index:
                continue
            self._last_index = index
            self._reconcile(allocs)

    def _reconcile(self, allocs: list[Allocation]) -> None:
        desired = {a.id: a for a in allocs}
        with self._lock:
            running = dict(self.runners)
        # stop / destroy
        for alloc_id, runner in running.items():
            a = desired.get(alloc_id)
            if a is None:
                runner.destroy()
                self.state_db.delete_alloc(alloc_id)
                self._acked_terminal.discard(alloc_id)
                self._drop_health_tracking(alloc_id)
                with self._lock:
                    self.runners.pop(alloc_id, None)
            elif a.desired_status in (ALLOC_DESIRED_STOP, "evict"):
                self._drop_health_tracking(alloc_id)
                if not runner._destroyed:
                    runner.stop()
        # start new
        for alloc_id, a in desired.items():
            if a.desired_status != ALLOC_DESIRED_RUN:
                continue
            if a.terminal_status() or alloc_id in running:
                continue
            self.state_db.put_alloc(a)
            runner = AllocRunner(
                a, self.drivers, self.data_dir,
                on_update=self._on_alloc_update,
                on_handle=self.state_db.put_handle,
                prev_watcher=self._watch_previous_alloc,
                device_plugins=self.device_plugins,
                device_group_owner=self.device_group_owner,
                csi_plugins=self.csi_plugins,
                csi_volume_resolver=self._csi_volume_resolver,
            )
            with self._lock:
                self.runners[alloc_id] = runner
            threading.Thread(
                target=runner.run, name=f"alloc-{alloc_id[:8]}", daemon=True
            ).start()
            self._maybe_track_health(runner)

    def _watch_previous_alloc(self, prev_id: str, timeout: float = 60.0):
        """allocwatcher (client/allocwatcher): block until the previous
        allocation's local runner reaches a terminal state; returns its
        alloc dir for migration. None ⇒ previous alloc is remote or
        already reclaimed (the reference would pull the dir over the
        node API; descoped to same-node migration)."""
        deadline = time.time() + timeout
        while time.time() < deadline and not self._stop.is_set():
            with self._lock:
                runner = self.runners.get(prev_id)
            if runner is None:
                return None
            if runner._destroyed or runner.is_terminal():
                return runner.alloc_dir
            time.sleep(0.05)
        return None

    # -- alloc health (client/allochealth tracker) -------------------------
    def _maybe_track_health(self, runner) -> None:
        """Deployment allocs get a health tracker: task states + service
        checks gate DeploymentStatus.Healthy (tracker.go). Checkless
        groups stay on the server-side continuous-running fallback."""
        alloc = runner.alloc
        if not getattr(alloc, "deployment_id", None):
            return
        from .allochealth import AllocHealthTracker, group_checks

        if not group_checks(alloc.job, alloc.task_group):
            return  # no checks: server-side task_states fallback applies
        job = alloc.job
        tg = job.lookup_task_group(alloc.task_group) if job else None
        tracker = AllocHealthTracker(
            runner,
            getattr(tg, "update", None),
            on_health=self._on_alloc_health,
        )
        with self._lock:
            self._health_trackers[alloc.id] = tracker
        tracker.start()

    def _drop_health_tracking(self, alloc_id: str) -> None:
        """Stop the tracker and prune the verdict when an alloc leaves
        this client (stopped/GC'd) — a live tracker would keep probing
        ports that may already belong to a new alloc."""
        with self._lock:
            tracker = self._health_trackers.pop(alloc_id, None)
            self._health_verdicts.pop(alloc_id, None)
        if tracker is not None:
            tracker.stop()

    def _on_alloc_health(self, alloc_id: str, healthy: bool) -> None:
        from ..structs.deployment import AllocDeploymentStatus

        with self._lock:
            self._health_verdicts[alloc_id] = healthy
            runner = self.runners.get(alloc_id)
        if runner is None:
            return
        upd = runner.alloc.copy_for_update()
        # client_status is the task lifecycle's to report — health is a
        # separate verdict; the verdict rides the regular alloc sync and
        # the store merges it onto the server copy for the watcher
        upd.deployment_status = AllocDeploymentStatus(
            healthy=healthy, timestamp_unix=time.time()
        )
        upd.task_states = {
            name: {
                "state": s.state,
                "failed": s.failed,
                "restarts": s.restarts,
            }
            for name, s in runner.task_states.items()
        }
        with self._lock:
            self._pending_updates[alloc_id] = upd
        self.state_db.put_alloc(upd)

    # -- status sync -------------------------------------------------------
    def _on_alloc_update(self, alloc: Allocation, status: str, task_states) -> None:
        upd = alloc.copy_for_update()
        upd.client_status = status
        upd.task_states = {
            name: {"state": s.state, "failed": s.failed, "restarts": s.restarts}
            for name, s in task_states.items()
        }
        verdict = self._health_verdicts.get(alloc.id)
        if verdict is not None:
            from ..structs.deployment import AllocDeploymentStatus

            upd.deployment_status = AllocDeploymentStatus(
                healthy=verdict, timestamp_unix=time.time()
            )
        with self._lock:
            self._pending_updates[alloc.id] = upd
        if self.publish_allocation_metrics:
            from ..utils.metrics import global_metrics

            global_metrics.incr(f"nomad.client.allocations.{status}")
        # keep the durable copy's status current so a restart doesn't
        # re-run an already-finished alloc
        self.state_db.put_alloc(upd)

    def _sync_loop(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(ALLOC_SYNC_INTERVAL)
            with self._lock:
                batch = list(self._pending_updates.values())
                self._pending_updates.clear()
            if batch:
                try:
                    self.rpc.update_allocs(batch)
                    self._acked_terminal.update(
                        u.id for u in batch if u.terminal_status()
                    )
                except Exception:
                    log.exception("alloc status sync failed")
                    with self._lock:
                        for u in batch:
                            self._pending_updates.setdefault(u.id, u)

    # -- introspection -----------------------------------------------------
    def num_allocs(self) -> int:
        with self._lock:
            return len(self.runners)
