"""Alloc health tracking — client/allochealth's Tracker analog.

Reference: client/allochealth/tracker.go — watches task states AND
service check results and sets ``DeploymentStatus.Healthy``, which the
deployment watcher consumes for canary auto-promotion / auto-revert
(nomad/deploymentwatcher). Without it, "running" is the only health bar
and a crash-looping-but-restarting task passes canary gates.

Semantics matched to the reference:

- healthy ⇔ every task is ``running`` AND every check has been passing
  CONTINUOUSLY for ``min_healthy_time`` (tracker.go's healthy timer);
- any task restart or check failure RESETS the clock (a flapping task
  never accumulates the window);
- a task reaching ``dead`` (restarts exhausted), or the
  ``healthy_deadline`` expiring before the window completes, reports
  UNHEALTHY — terminal for this alloc's deployment health (the reference
  only flips healthy→unhealthy on failure, never back);
- checks: tcp connect / http GET (2xx-3xx) / script exit-0 — evaluated
  in-process (the reference delegates to Consul; this build has no
  Consul, matching SURVEY's de-scope, so the client evaluates directly).

The tracker reports through a callback the client wires into its alloc
sync batch — the health verdict rides the same Node.UpdateAlloc path as
task states, and the FSM merges it onto the server copy
(state/store.update_allocs_from_client).
"""

from __future__ import annotations

import http.client
import socket
import subprocess
import threading
import time
from typing import Callable, Optional

POLL_INTERVAL = 0.2


def evaluate_check(check) -> bool:
    """One check evaluation. Returns True when passing."""
    try:
        if check.type == "tcp":
            with socket.create_connection(
                (check.address, check.port), timeout=check.timeout_s
            ):
                return True
        if check.type == "http":
            conn = http.client.HTTPConnection(
                check.address, check.port, timeout=check.timeout_s
            )
            try:
                conn.request("GET", check.path or "/")
                resp = conn.getresponse()
                resp.read()
                return 200 <= resp.status < 400
            finally:
                conn.close()
        if check.type == "script":
            out = subprocess.run(
                [check.command] + list(check.args),
                timeout=check.timeout_s,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            return out.returncode == 0
    except (OSError, subprocess.TimeoutExpired, ValueError):
        return False
    return False


def group_checks(job, group_name: str) -> list:
    tg = job.lookup_task_group(group_name) if job else None
    if tg is None:
        return []
    out = []
    for task in tg.tasks:
        for svc in getattr(task, "services", None) or []:
            out.extend(svc.checks or [])
    return out


class AllocHealthTracker:
    """Watches one alloc runner until a health verdict is reached."""

    def __init__(
        self,
        runner,
        update_strategy,
        on_health: Callable[[str, bool], None],
        min_healthy_time_s: Optional[float] = None,
        healthy_deadline_s: Optional[float] = None,
    ):
        self.runner = runner
        self.alloc = runner.alloc
        self.checks = group_checks(self.alloc.job, self.alloc.task_group)
        self.on_health = on_health
        u = update_strategy
        self.min_healthy = (
            min_healthy_time_s
            if min_healthy_time_s is not None
            else (u.min_healthy_time_s if u else 10.0)
        )
        self.deadline = (
            healthy_deadline_s
            if healthy_deadline_s is not None
            else (u.healthy_deadline_s if u else 300.0)
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.result: Optional[bool] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run,
            name=f"allochealth-{self.alloc.id[:8]}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout=None) -> None:
        if self._thread:
            self._thread.join(timeout)

    # -- internals ---------------------------------------------------------
    def _tasks_running(self) -> tuple[bool, bool, int]:
        """(all_running, any_dead, total_restarts) from the runner's live
        task states."""
        states = self.runner.task_states
        if not states:
            return False, False, 0
        all_running = all(s.state == "running" for s in states.values())
        any_dead = any(
            s.state == "dead" and s.failed for s in states.values()
        )
        restarts = sum(s.restarts for s in states.values())
        return all_running, any_dead, restarts

    def _checks_pass(self) -> bool:
        return all(evaluate_check(c) for c in self.checks)

    def _run(self) -> None:
        deadline = time.time() + self.deadline
        window_start: Optional[float] = None
        baseline_restarts = 0
        next_check_at = 0.0
        checks_ok = not self.checks
        check_interval = min(
            [c.interval_s for c in self.checks] or [1.0]
        )
        while not self._stop.is_set():
            now = time.time()
            all_running, any_dead, restarts = self._tasks_running()
            if any_dead:
                return self._report(False)
            if now >= next_check_at and self.checks:
                checks_ok = self._checks_pass()
                next_check_at = now + check_interval
            if all_running and checks_ok:
                if window_start is None:
                    window_start = now
                    baseline_restarts = restarts
                elif restarts != baseline_restarts:
                    # a restart mid-window: flapping — start over
                    window_start = now
                    baseline_restarts = restarts
                elif now - window_start >= self.min_healthy:
                    return self._report(True)
            else:
                window_start = None  # failure resets the clock
            if now >= deadline:
                return self._report(False)
            self._stop.wait(POLL_INTERVAL)

    def _report(self, healthy: bool) -> None:
        self.result = healthy
        try:
            self.on_health(self.alloc.id, healthy)
        except Exception:  # pragma: no cover — callback owns its errors
            import logging

            logging.getLogger(__name__).exception(
                "alloc health callback failed"
            )
