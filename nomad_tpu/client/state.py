"""Client local state — restore-on-restart of alloc/task state.

Reference: client/state/ (StateDB over BoltDB via helper/boltdd): the
client persists each alloc it is running plus per-task driver handles, so
a restarted client re-attaches to live tasks instead of killing them
(client restore path client/client.go + task_runner.go:488-519).

Here the store is the native WAL's durable KV (nomad_tpu.native) — the
same BoltDB role it plays for the server's term/vote. The live view is a
pair of in-memory maps of pre-pickled records (alloc id → bytes,
(alloc id, task) → bytes) flushed as one atomic whole-file write per
mutation — matching the KV backend's whole-file atomicity; per-record
bolt buckets would add no durability granularity here.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Dict, Optional

from ..native import WalStore


class ClientStateDB:
    def __init__(self, data_dir: str):
        os.makedirs(data_dir, exist_ok=True)
        self._wal = WalStore(os.path.join(data_dir, "client-state"))
        self._lock = threading.Lock()
        self._closed = False
        # the KV is whole-file persisted; maintain the live view in memory
        self._allocs: Dict[str, bytes] = {}
        self._handles: Dict[tuple, bytes] = {}
        self._load()

    def _load(self) -> None:
        raw = self._wal.kv_get("state")
        if not raw:
            return
        try:
            data = pickle.loads(raw)
        except Exception:
            return
        self._allocs = data.get("allocs", {})
        self._handles = data.get("handles", {})

    def _flush(self) -> None:
        if self._closed:
            # shutdown raced a still-running task thread's final status
            # write; the restart reconciles against server state anyway
            return
        self._wal.kv_set(
            "state",
            pickle.dumps(
                {"allocs": self._allocs, "handles": self._handles},
                pickle.HIGHEST_PROTOCOL,
            ),
        )

    # -- allocs ------------------------------------------------------------
    def put_alloc(self, alloc) -> None:
        with self._lock:
            self._allocs[alloc.id] = pickle.dumps(
                alloc, pickle.HIGHEST_PROTOCOL
            )
            self._flush()

    def delete_alloc(self, alloc_id: str) -> None:
        with self._lock:
            self._allocs.pop(alloc_id, None)
            for key in [k for k in self._handles if k[0] == alloc_id]:
                self._handles.pop(key, None)
            self._flush()

    def allocs(self) -> list:
        with self._lock:
            out = []
            for raw in self._allocs.values():
                try:
                    out.append(pickle.loads(raw))
                except Exception:
                    continue
            return out

    # -- task handles ------------------------------------------------------
    def put_handle(self, alloc_id: str, task_name: str, handle) -> None:
        with self._lock:
            self._handles[(alloc_id, task_name)] = pickle.dumps(
                handle, pickle.HIGHEST_PROTOCOL
            )
            self._flush()

    def handles_for(self, alloc_id: str) -> Dict[str, object]:
        with self._lock:
            out = {}
            for (aid, name), raw in self._handles.items():
                if aid != alloc_id:
                    continue
                try:
                    out[name] = pickle.loads(raw)
                except Exception:
                    continue
            return out

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._wal.sync()
            self._wal.close()
