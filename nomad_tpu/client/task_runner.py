"""TaskRunner — per-task lifecycle state machine.

Reference: client/allocrunner/taskrunner/task_runner.go (the MAIN/RESTART
loop :480-640): prestart hooks → driver start → wait → restart decision
per RestartPolicy (attempts within interval, delay, fail/delay modes) →
terminal state. Hook phases are collapsed to env build + task dir here;
artifact/template/vault hooks attach in later layers.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..structs import Task
from ..structs.job import RestartPolicy
from .drivers import DriverError, TaskDriver, TaskHandle

TASK_EVENT_STARTED = "Started"
TASK_EVENT_TERMINATED = "Terminated"
TASK_EVENT_RESTARTING = "Restarting"
TASK_EVENT_NOT_RESTARTING = "Not Restarting"
TASK_EVENT_DRIVER_ERROR = "Driver Failure"
TASK_EVENT_KILLING = "Killing"


@dataclass
class TaskEvent:
    type: str
    time_unix: float = field(default_factory=time.time)
    message: str = ""
    exit_code: Optional[int] = None


@dataclass
class TaskState:
    """structs.TaskState: the client-reported per-task status."""

    state: str = "pending"  # pending | running | dead
    failed: bool = False
    restarts: int = 0
    events: list[TaskEvent] = field(default_factory=list)
    started_at: float = 0.0
    finished_at: float = 0.0

    def record(self, ev: TaskEvent) -> None:
        self.events.append(ev)
        if len(self.events) > 10:  # bounded event history
            self.events = self.events[-10:]


class TaskRunner:
    def __init__(
        self,
        task: Task,
        driver: TaskDriver,
        task_dir: str,
        env: dict[str, str],
        restart_policy: Optional[RestartPolicy] = None,
        on_state_change=None,
        attach_handle: Optional[TaskHandle] = None,
        on_handle=None,
    ):
        self.task = task
        self.driver = driver
        self.task_dir = task_dir
        self.env = env
        self.restart_policy = restart_policy or RestartPolicy()
        self.state = TaskState()
        self.handle: Optional[TaskHandle] = None
        self.on_state_change = on_state_change
        # restore path (task_runner.go:488-519): a persisted handle the
        # driver successfully recovered — skip the first driver.start
        self.attach_handle = attach_handle
        self.on_handle = on_handle  # persists handles for restart restore
        self._kill = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._restart_times: list[float] = []

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, name=f"task-{self.task.name}", daemon=True
        )
        self._thread.start()

    def kill(self, timeout: float = 5.0) -> None:
        self._kill.set()
        self.state.record(TaskEvent(TASK_EVENT_KILLING))
        if self.handle is not None:
            self.driver.stop(self.handle, kill_timeout=self.task.kill_timeout_s)
        if self._thread is not None:
            self._thread.join(timeout=timeout + 1)

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    # -- main loop (task_runner.go:480 MAIN) -------------------------------
    def run(self) -> None:
        os.makedirs(self.task_dir, exist_ok=True)
        while not self._kill.is_set():
            if self.attach_handle is not None:
                self.handle = self.attach_handle
                self.attach_handle = None  # restarts go through start()
            else:
                try:
                    self.handle = self.driver.start(
                        self.task, self._task_env(), self.task_dir
                    )
                except DriverError as e:
                    self.state.record(
                        TaskEvent(TASK_EVENT_DRIVER_ERROR, message=str(e))
                    )
                    if not self._should_restart(failed=True):
                        break
                    continue
            if self.on_handle is not None and self.handle is not None:
                self.on_handle(self.task.name, self.handle)

            self.state.state = "running"
            self.state.started_at = self.state.started_at or time.time()
            self.state.record(TaskEvent(TASK_EVENT_STARTED))
            self._notify()

            exit_code = self.driver.wait(self.handle)
            self.state.record(
                TaskEvent(TASK_EVENT_TERMINATED, exit_code=exit_code)
            )
            if self._kill.is_set():
                break
            if exit_code == 0:
                self.state.failed = False
                break
            if not self._should_restart(failed=True):
                break

        self.state.state = "dead"
        self.state.finished_at = time.time()
        # a deliberately killed task (stop/drain) is not a failure —
        # mirrors task_runner.go's kill-vs-fail distinction
        if (
            not self._kill.is_set()
            and self.state.events
            and self.state.events[-1].type == TASK_EVENT_TERMINATED
            and self.state.events[-1].exit_code not in (0, None)
        ):
            self.state.failed = True
        self._notify()

    def _task_env(self) -> dict[str, str]:
        """Task env interpolation (client/taskenv)."""
        env = dict(self.env)
        env.update(self.task.env)
        env["NOMAD_TASK_NAME"] = self.task.name
        env["NOMAD_TASK_DIR"] = os.path.join(self.task_dir, "local")
        os.makedirs(env["NOMAD_TASK_DIR"], exist_ok=True)
        return env

    def _should_restart(self, failed: bool) -> bool:
        """RestartPolicy window check (task_runner.go restart tracking):
        up to ``attempts`` restarts per ``interval``; mode=fail ⇒ give up,
        mode=delay ⇒ wait out the interval."""
        pol = self.restart_policy
        now = time.time()
        window_start = now - pol.interval_s
        self._restart_times = [t for t in self._restart_times if t >= window_start]
        if len(self._restart_times) >= pol.attempts:
            if pol.mode == "delay":
                self.state.record(
                    TaskEvent(TASK_EVENT_RESTARTING, message="delaying past window")
                )
                if self._kill.wait(pol.interval_s):
                    return False
                self._restart_times.clear()
            else:
                self.state.record(TaskEvent(TASK_EVENT_NOT_RESTARTING))
                self.state.failed = True
                return False
        self._restart_times.append(now)
        self.state.restarts += 1
        self.state.record(TaskEvent(TASK_EVENT_RESTARTING))
        if self._kill.wait(pol.delay_s):
            return False
        return True

    def _notify(self) -> None:
        if self.on_state_change is not None:
            self.on_state_change(self.task.name, self.state)
