"""Out-of-process DEVICE plugin contract — the device.proto analog.

Reference: plugins/device/proto/device.proto + plugins/device/device.go:
a device plugin is a separate process the client talks to over a typed
contract with three calls — ``Fingerprint`` (stream of detected device
groups), ``Reserve`` (instance ids → container/env mutations), and
``Stats`` (per-instance usage). The reference speaks gRPC to a hashicorp
go-plugin binary; this build reuses the framework's NDJSON stdio plugin
transport (client/plugin.py's wire style), so device plugins get the
same lifecycle/reattach properties as driver plugins without a protobuf
toolchain.

Wire protocol (one JSON object per line):
  plugin → host  {"type": "handshake", "magic": ..., "version": 1,
                  "plugin": "<name>"}
  host → plugin  {"id": N, "method": "fingerprint" | "reserve" | "stats",
                  "params": {...}}
  plugin → host  {"id": N, "result": ...} | {"id": N, "error": "..."}

A plugin is any executable speaking this protocol; the builtin launcher
(``python -m nomad_tpu.client.device_plugin <name>``) serves the
plugins registered in BUILTIN_DEVICE_PLUGINS (the jax/TPU plugin and a
test fake), mirroring how driver plugins are spawned.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

from ..structs.resources import NodeDeviceInstance, NodeDeviceResource
from .stdio_plugin import StdioPluginClient, serve_stdio_plugin

DEVICE_PLUGIN_MAGIC = "NOMAD_TPU_DEVICE_V1"
DEVICE_PROTO_VERSION = 1


class DevicePlugin:
    """Base class for the plugin-side implementation."""

    name = "device"

    def fingerprint(self) -> list[dict]:
        """Detected device groups: [{vendor, type, name, instances:
        [{id, healthy}], attributes: {...}}]."""
        return []

    def reserve(self, device_ids: list[str]) -> dict:
        """Reservation response (device.proto ContainerReservation):
        {"envs": {...}, "mounts": [...], "devices": [...]}."""
        return {"envs": {}, "mounts": [], "devices": []}

    def stats(self) -> dict:
        """Per-instance stats: {instance_id: {...}}."""
        return {}


class JaxDevicePlugin(DevicePlugin):
    """The native accelerator plugin: surfaces the jax device table (the
    TPU) as a schedulable device group — the drivers/gpu analog for this
    framework's own hardware."""

    name = "jax"

    def fingerprint(self) -> list[dict]:
        try:
            import jax

            accel = [
                d for d in jax.devices() if d.platform not in ("cpu",)
            ]
        except Exception:  # noqa: BLE001 — no backend = no devices
            return []
        if not accel:
            return []
        platform = accel[0].platform
        return [
            {
                "vendor": "google",
                "type": "tpu" if platform == "tpu" else platform,
                "name": getattr(
                    accel[0], "device_kind", platform
                ).replace(" ", "-").lower(),
                "instances": [
                    {"id": f"{platform}-{d.id}", "healthy": True}
                    for d in accel
                ],
                "attributes": {"count": len(accel)},
            }
        ]

    def reserve(self, device_ids: list[str]) -> dict:
        ordinals = ",".join(
            did.rsplit("-", 1)[-1] for did in device_ids
        )
        # the visibility knobs the runtimes actually honor: the TPU
        # runtime reads TPU_VISIBLE_CHIPS (newer) / TPU_VISIBLE_DEVICES
        # (older); CUDA backends read CUDA_VISIBLE_DEVICES
        return {
            "envs": {
                "TPU_VISIBLE_CHIPS": ordinals,
                "TPU_VISIBLE_DEVICES": ordinals,
                "CUDA_VISIBLE_DEVICES": ordinals,
            },
            "mounts": [],
            "devices": [],
        }


class FakeDevicePlugin(DevicePlugin):
    """Deterministic test plugin: devices configured via env."""

    name = "fake"

    def fingerprint(self) -> list[dict]:
        spec = os.environ.get("NOMAD_FAKE_DEVICES", "")
        if not spec:
            return []
        # "vendor/type/name:n"
        head, _, n = spec.partition(":")
        vendor, type_, name = head.split("/")
        return [
            {
                "vendor": vendor,
                "type": type_,
                "name": name,
                "instances": [
                    {"id": f"{name}-{i}", "healthy": True}
                    for i in range(int(n or 1))
                ],
                "attributes": {"memory_mb": 1024},
            }
        ]

    def reserve(self, device_ids: list[str]) -> dict:
        return {
            "envs": {"FAKE_VISIBLE_DEVICES": ",".join(device_ids)},
            "mounts": [],
            "devices": [f"/dev/fake/{d}" for d in device_ids],
        }

    def stats(self) -> dict:
        return {
            d["id"]: {"utilization": 0.0}
            for g in self.fingerprint()
            for d in g["instances"]
        }


BUILTIN_DEVICE_PLUGINS = {
    p.name: p for p in (JaxDevicePlugin(), FakeDevicePlugin())
}


# -- plugin (server) side ----------------------------------------------------


def serve_device_plugin(plugin: DevicePlugin, stdin=None, stdout=None):
    serve_stdio_plugin(
        DEVICE_PLUGIN_MAGIC,
        DEVICE_PROTO_VERSION,
        plugin.name,
        {
            "fingerprint": lambda p: plugin.fingerprint(),
            "reserve": lambda p: plugin.reserve(
                p.get("device_ids") or []
            ),
            "stats": lambda p: plugin.stats(),
        },
        stdin=stdin,
        stdout=stdout,
    )


# -- host (client) side ------------------------------------------------------


class DevicePluginClient(StdioPluginClient):
    """Spawns and drives one device plugin subprocess."""

    MAGIC = DEVICE_PLUGIN_MAGIC
    VERSION = DEVICE_PROTO_VERSION

    def default_argv(self, name: str) -> list[str]:
        return [
            sys.executable, "-m", "nomad_tpu.client.device_plugin", name,
        ]

    # -- contract ----------------------------------------------------------
    def fingerprint(self) -> list[NodeDeviceResource]:
        groups = self._call("fingerprint") or []
        out = []
        for g in groups:
            out.append(
                NodeDeviceResource(
                    vendor=g.get("vendor", ""),
                    type=g.get("type", ""),
                    name=g.get("name", ""),
                    instances=[
                        NodeDeviceInstance(
                            id=i.get("id", ""),
                            healthy=bool(i.get("healthy", True)),
                        )
                        for i in g.get("instances", [])
                    ],
                    attributes=dict(g.get("attributes") or {}),
                )
            )
        return out

    def reserve(self, device_ids: list[str]) -> dict:
        return self._call("reserve", {"device_ids": device_ids}) or {}

    def stats(self) -> dict:
        return self._call("stats") or {}


def _main() -> None:
    name = sys.argv[1] if len(sys.argv) > 1 else "fake"
    plugin = BUILTIN_DEVICE_PLUGINS.get(name)
    if plugin is None:
        print(f"unknown device plugin {name!r}", file=sys.stderr)
        raise SystemExit(2)
    serve_device_plugin(plugin)


if __name__ == "__main__":
    _main()
