"""L3/L4 client layer: node agent, runners, drivers, fingerprinting."""

from .alloc_runner import AllocRunner
from .client import Client, ServerRPC
from .drivers import (
    ExecDriver,
    MockDriver,
    RawExecDriver,
    TaskDriver,
    TaskHandle,
    builtin_drivers,
)
from .fingerprint import fingerprint_node
from .task_runner import TaskRunner, TaskState

__all__ = [
    "AllocRunner",
    "Client",
    "ServerRPC",
    "TaskDriver",
    "TaskHandle",
    "MockDriver",
    "RawExecDriver",
    "ExecDriver",
    "builtin_drivers",
    "fingerprint_node",
    "TaskRunner",
    "TaskState",
]
