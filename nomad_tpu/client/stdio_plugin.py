"""Shared NDJSON-stdio plugin transport (host side).

The device (client/device_plugin.py) and CSI (client/csi_plugin.py)
plugin clients speak the same wire: spawn a subprocess, read one
handshake line under a deadline, then serial request/response JSON
lines. This base owns that machinery once — transport fixes (handshake
deadlines, zombie reaping, respawn) apply everywhere. The DRIVER plugin
client (client/plugin.py) keeps its own pipelined transport: it
multiplexes long-blocking calls (wait) concurrently, which this serial
base deliberately does not."""

from __future__ import annotations

import json
import os
import select
import subprocess
import sys
import threading
import time
from typing import Optional

HANDSHAKE_TIMEOUT_S = 10.0


def serve_stdio_plugin(
    magic: str,
    version: int,
    plugin_name: str,
    methods: dict,
    stdin=None,
    stdout=None,
) -> None:
    """Plugin-side serve loop shared by the device and CSI plugins:
    handshake line, then serial id/method/params dispatch with error
    replies; ``shutdown`` exits. ``methods`` maps method name → callable
    taking the params dict."""
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    wlock = threading.Lock()

    def send(obj: dict) -> None:
        with wlock:
            stdout.write(json.dumps(obj) + "\n")
            stdout.flush()

    send(
        {
            "type": "handshake",
            "magic": magic,
            "version": version,
            "plugin": plugin_name,
        }
    )
    for line in stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
        except json.JSONDecodeError:
            continue
        rid = req.get("id")
        method = req.get("method", "")
        if method == "shutdown":
            send({"id": rid, "result": True})
            return
        fn = methods.get(method)
        if fn is None:
            send({"id": rid, "error": f"unknown method {method!r}"})
            continue
        try:
            send({"id": rid, "result": fn(req.get("params") or {})})
        except Exception as e:  # noqa: BLE001 — report, don't die
            send({"id": rid, "error": str(e)})


class StdioPluginClient:
    """Serial request/response client over a plugin subprocess's stdio."""

    #: subclasses set these
    MAGIC = ""
    VERSION = 0

    def __init__(self, name: str, argv: Optional[list[str]] = None):
        self.name = name
        self._argv = argv or self.default_argv(name)
        self._proc: Optional[subprocess.Popen] = None
        self._lock = threading.Lock()
        self._next_id = 0

    def default_argv(self, name: str) -> list[str]:  # pragma: no cover
        raise NotImplementedError

    def _ensure(self) -> None:
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                return
            self._proc = subprocess.Popen(
                self._argv,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                text=True,
                env={**os.environ, "PYTHONPATH": os.pathsep.join(sys.path)},
            )
            # bounded handshake covering partial lines: a hung or
            # misbehaving plugin must not wedge the caller
            deadline = time.monotonic() + HANDSHAKE_TIMEOUT_S
            fd = self._proc.stdout.fileno()
            buf = b""
            while b"\n" not in buf:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._proc.kill()
                    self._proc.wait()
                    raise RuntimeError(
                        f"plugin {self.name!r} handshake timeout"
                    )
                ready, _, _ = select.select([fd], [], [], remaining)
                if not ready:
                    continue
                chunk = os.read(fd, 4096)
                if not chunk:
                    break
                buf += chunk
            try:
                hs = json.loads(buf.partition(b"\n")[0] or b"{}")
            except ValueError:
                hs = {}  # garbage banner: fail the magic check below
            if hs.get("magic") != self.MAGIC or (
                hs.get("version") != self.VERSION
            ):
                self._proc.kill()
                self._proc.wait()  # reap — no zombie on mismatch
                raise RuntimeError(
                    f"plugin {self.name!r} handshake failed: {hs!r}"
                )

    def _call(self, method: str, params: Optional[dict] = None):
        self._ensure()
        with self._lock:
            self._next_id += 1
            rid = self._next_id
            self._proc.stdin.write(
                json.dumps(
                    {"id": rid, "method": method, "params": params or {}}
                )
                + "\n"
            )
            self._proc.stdin.flush()
            line = self._proc.stdout.readline()
        if not line:
            raise RuntimeError(f"plugin {self.name!r} exited")
        try:
            msg = json.loads(line)
        except ValueError as e:
            # a stray non-JSON line must surface through the transport's
            # RuntimeError contract, not as a JSONDecodeError callers
            # don't expect
            raise RuntimeError(
                f"plugin {self.name!r} sent invalid response: "
                f"{line[:120]!r}"
            ) from e
        if msg.get("error"):
            raise RuntimeError(msg["error"])
        return msg.get("result")

    def close(self) -> None:
        p = self._proc
        if p is None:
            return
        if p.poll() is None:
            # only a LIVE plugin gets the polite shutdown — calling
            # _call() here would respawn a dead one just to kill it
            try:
                self._call("shutdown")
            except Exception:  # noqa: BLE001
                pass
        try:
            p.terminate()
            p.wait(timeout=2)
        except Exception:  # noqa: BLE001
            p.kill()
            try:
                p.wait(timeout=2)
            except Exception:  # noqa: BLE001
                pass
        self._proc = None
