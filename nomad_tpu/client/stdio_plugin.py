"""Shared NDJSON-stdio plugin transport (host side).

The device (client/device_plugin.py) and CSI (client/csi_plugin.py)
plugin clients speak the same wire: spawn a subprocess, read one
handshake line under a deadline, then serial request/response JSON
lines. This base owns that machinery once — transport fixes (handshake
deadlines, zombie reaping, respawn) apply everywhere. The DRIVER plugin
client (client/plugin.py) keeps its own pipelined transport: it
multiplexes long-blocking calls (wait) concurrently, which this serial
base deliberately does not."""

from __future__ import annotations

import json
import os
import select
import subprocess
import sys
import threading
import time
from typing import Optional

HANDSHAKE_TIMEOUT_S = 10.0


class StdioPluginClient:
    """Serial request/response client over a plugin subprocess's stdio."""

    #: subclasses set these
    MAGIC = ""
    VERSION = 0

    def __init__(self, name: str, argv: Optional[list[str]] = None):
        self.name = name
        self._argv = argv or self.default_argv(name)
        self._proc: Optional[subprocess.Popen] = None
        self._lock = threading.Lock()
        self._next_id = 0

    def default_argv(self, name: str) -> list[str]:  # pragma: no cover
        raise NotImplementedError

    def _ensure(self) -> None:
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                return
            self._proc = subprocess.Popen(
                self._argv,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                text=True,
                env={**os.environ, "PYTHONPATH": os.pathsep.join(sys.path)},
            )
            # bounded handshake covering partial lines: a hung or
            # misbehaving plugin must not wedge the caller
            deadline = time.monotonic() + HANDSHAKE_TIMEOUT_S
            fd = self._proc.stdout.fileno()
            buf = b""
            while b"\n" not in buf:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._proc.kill()
                    self._proc.wait()
                    raise RuntimeError(
                        f"plugin {self.name!r} handshake timeout"
                    )
                ready, _, _ = select.select([fd], [], [], remaining)
                if not ready:
                    continue
                chunk = os.read(fd, 4096)
                if not chunk:
                    break
                buf += chunk
            hs = json.loads(buf.partition(b"\n")[0] or b"{}")
            if hs.get("magic") != self.MAGIC or (
                hs.get("version") != self.VERSION
            ):
                self._proc.kill()
                self._proc.wait()  # reap — no zombie on mismatch
                raise RuntimeError(
                    f"plugin {self.name!r} handshake failed: {hs!r}"
                )

    def _call(self, method: str, params: Optional[dict] = None):
        self._ensure()
        with self._lock:
            self._next_id += 1
            rid = self._next_id
            self._proc.stdin.write(
                json.dumps(
                    {"id": rid, "method": method, "params": params or {}}
                )
                + "\n"
            )
            self._proc.stdin.flush()
            line = self._proc.stdout.readline()
        if not line:
            raise RuntimeError(f"plugin {self.name!r} exited")
        msg = json.loads(line)
        if msg.get("error"):
            raise RuntimeError(msg["error"])
        return msg.get("result")

    def close(self) -> None:
        p = self._proc
        if p is None:
            return
        if p.poll() is None:
            # only a LIVE plugin gets the polite shutdown — calling
            # _call() here would respawn a dead one just to kill it
            try:
                self._call("shutdown")
            except Exception:  # noqa: BLE001
                pass
        try:
            p.terminate()
            p.wait(timeout=2)
        except Exception:  # noqa: BLE001
            p.kill()
            try:
                p.wait(timeout=2)
            except Exception:  # noqa: BLE001
                pass
        self._proc = None
