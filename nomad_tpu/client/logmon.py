"""logmon — task log retention by copy-truncate rotation.

Reference: client/logmon/ (the per-task log-shipper subprocess rotating
FIFO-fed logs under structs.LogConfig: MaxFiles × MaxFileSizeMB, default
10 × 10 MiB). This build's drivers redirect task stdio straight into
files (no FIFO hop), so rotation is copy-truncate: when a stream file
exceeds its cap, the suffixed history shifts (.0 newest … .N oldest,
oldest dropped), the current content is copied to ``.0``, and the live
file is truncated in place — the writer's file descriptor stays valid, no
writer cooperation needed. The fs/logs HTTP endpoints keep serving the
live file; history rides beside it in the task dir.
"""

from __future__ import annotations

import logging
import os
import shutil

log = logging.getLogger("nomad_tpu.logmon")


def rotate_if_needed(path: str, max_files: int, max_file_size_mb: int) -> bool:
    """Rotate one stream file when it exceeds its cap. Returns True when
    a rotation happened."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return False
    if size <= max_file_size_mb * 1024 * 1024:
        return False
    # MaxFiles counts TOTAL files including the live one (structs.LogConfig),
    # so history slots = max_files − 1; max_files=1 ⇒ pure truncation
    history = max(max_files - 1, 0)
    try:
        if history > 0:
            # shift .(h-2) → .(h-1) …; os.replace overwrites, so the
            # oldest slot is dropped by the first shift
            for i in range(history - 2, -1, -1):
                src = f"{path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{path}.{i + 1}")
            # copy-truncate: the writing process keeps its fd
            shutil.copyfile(path, f"{path}.0")
        with open(path, "r+b") as f:
            f.truncate(0)
        return True
    except OSError:
        log.exception("log rotation failed for %s", path)
        return False


def sweep_alloc(runner) -> int:
    """Rotate every task stream of one alloc runner per its task's
    LogConfig. Returns rotations performed."""
    alloc = runner.alloc
    job = alloc.job
    tg = job.lookup_task_group(alloc.task_group) if job else None
    if tg is None:
        return 0
    n = 0
    for task in tg.tasks:
        lc = getattr(task, "log_config", None)
        if lc is None:
            continue
        task_dir = os.path.join(runner.alloc_dir, task.name)
        for stream in ("stdout", "stderr"):
            if rotate_if_needed(
                os.path.join(task_dir, f"{task.name}.{stream}"),
                lc.max_files,
                lc.max_file_size_mb,
            ):
                n += 1
    return n
