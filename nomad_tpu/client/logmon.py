"""logmon — task log retention by copy-truncate rotation.

Reference: client/logmon/ (the per-task log-shipper subprocess rotating
FIFO-fed logs under structs.LogConfig: MaxFiles × MaxFileSizeMB, default
10 × 10 MiB). This build's drivers redirect task stdio straight into
files (no FIFO hop), so rotation is copy-truncate: when a stream file
exceeds its cap, the suffixed history shifts (.0 newest … .N oldest,
oldest dropped), the current content is copied to ``.0``, and the live
file is truncated in place — the writer's file descriptor stays valid, no
writer cooperation needed. The fs/logs HTTP endpoints keep serving the
live file; history rides beside it in the task dir.

Trade-off vs the reference's FIFO logmon: copy-truncate is not lossless.
Bytes the task writes between the snapshot copy and the truncate are
dropped. The window is shrunk by copying exactly the snapshot size
(os.pread up to that offset) and, when the file grew during the copy,
re-copying the tail before truncating to zero — but a write that lands
between the final size check and ftruncate is still lost. The reference
avoids this by owning the write path (a FIFO the logmon drains); that
needs writer cooperation this build's direct-to-file drivers don't have.
"""

from __future__ import annotations

import logging
import os
import shutil

log = logging.getLogger("nomad_tpu.logmon")


def rotate_if_needed(path: str, max_files: int, max_file_size_mb: int) -> bool:
    """Rotate one stream file when it exceeds its cap. Returns True when
    a rotation happened."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return False
    if size <= max_file_size_mb * 1024 * 1024:
        return False
    # MaxFiles counts TOTAL files including the live one (structs.LogConfig),
    # so history slots = max_files − 1; max_files=1 ⇒ pure truncation
    history = max(max_files - 1, 0)
    try:
        if history > 0:
            # shift .(h-2) → .(h-1) …; os.replace overwrites, so the
            # oldest slot is dropped by the first shift
            for i in range(history - 2, -1, -1):
                src = f"{path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{path}.{i + 1}")
            # copy-truncate with a minimized loss window: copy under one
            # read fd, then re-copy any tail the writer appended during
            # the copy, and only then truncate. A write landing between
            # the final fstat and ftruncate is still lost (documented
            # module-level trade-off vs the reference's FIFO logmon).
            fd = os.open(path, os.O_RDONLY)
            try:
                with open(f"{path}.0", "wb") as dst:
                    copied = 0
                    while True:
                        chunk = os.pread(fd, 1 << 20, copied)
                        if not chunk:
                            break
                        dst.write(chunk)
                        copied += len(chunk)
                    # tail grown during the copy loop's last read?
                    end = os.fstat(fd).st_size
                    while copied < end:
                        chunk = os.pread(fd, 1 << 20, copied)
                        if not chunk:
                            break
                        dst.write(chunk)
                        copied += len(chunk)
                        end = os.fstat(fd).st_size
            finally:
                os.close(fd)
        with open(path, "r+b") as f:
            f.truncate(0)
        return True
    except OSError:
        log.exception("log rotation failed for %s", path)
        return False


def sweep_alloc(runner) -> int:
    """Rotate every task stream of one alloc runner per its task's
    LogConfig. Returns rotations performed."""
    alloc = runner.alloc
    job = alloc.job
    tg = job.lookup_task_group(alloc.task_group) if job else None
    if tg is None:
        return 0
    n = 0
    for task in tg.tasks:
        lc = getattr(task, "log_config", None)
        if lc is None:
            continue
        task_dir = os.path.join(runner.alloc_dir, task.name)
        for stream in ("stdout", "stderr"):
            if rotate_if_needed(
                os.path.join(task_dir, f"{task.name}.{stream}"),
                lc.max_files,
                lc.max_file_size_mb,
            ):
                n += 1
    return n
