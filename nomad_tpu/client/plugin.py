"""Out-of-process driver plugins — the subprocess driver contract.

Reference: plugins/drivers/proto/driver.proto (TaskDriver gRPC service:
Fingerprint/StartTask/WaitTask/StopTask/RecoverTask) + plugins/base
(handshake with a magic cookie + protocol version) +
plugins/drivers/task_handle.go (reattach handles that survive both task
and client restarts).

Transport: NDJSON request/response over the plugin's stdin/stdout with
pipelined request ids — the reference's gRPC-over-unix-socket carries the
same five verbs; JSON framing keeps the protocol dependency-free (no
protoc/grpc codegen in this toolchain) while preserving the contract:

  plugin → host  {"type":"handshake","magic":...,"version":1,
                  "driver":name,"fingerprint":bool}
  host → plugin  {"id":N,"method":"start|wait|stop|recover|inspect|
                  fingerprint|shutdown","params":{...}}
  plugin → host  {"id":N,"result":...} | {"id":N,"error":"..."}

``wait`` blocks server-side per task, so requests are handled on one
thread per request and responses are matched by id host-side — several
tasks run concurrently through one plugin process, as with the
reference's multiplexed gRPC connection.

Reattach: task processes are started in their own sessions (setsid), so
they survive BOTH the plugin process and the client dying; a restarted
client spawns a fresh plugin and hands it the persisted TaskHandle via
``recover`` (pid + kernel start time identity, drivers.py)."""

from __future__ import annotations

import json
import os
import select
import subprocess
import sys
import threading
import time
from dataclasses import asdict
from typing import Optional

from .drivers import DriverError, TaskDriver, TaskHandle

PLUGIN_MAGIC = "NOMAD_TPU_DRIVER_V1"
PROTO_VERSION = 1
# a spawned plugin must write its handshake line within this window or be
# killed (a hung plugin would otherwise block every driver call)
HANDSHAKE_TIMEOUT_S = 10.0


def _handle_to_wire(h: TaskHandle) -> dict:
    return asdict(h)


def _handle_from_wire(d: dict) -> TaskHandle:
    return TaskHandle(**d)


class _WireRes:
    __slots__ = ("cpu", "memory_mb")

    def __init__(self, cpu: int, memory_mb: int):
        self.cpu = cpu
        self.memory_mb = memory_mb


class _WireTask:
    """Minimal task view the plugin needs (name/driver/config/resources —
    the exec driver derives its rlimits from the memory ask)."""

    __slots__ = ("name", "driver", "config", "resources")

    def __init__(self, name: str, driver: str, config: dict, resources=None):
        self.name = name
        self.driver = driver
        self.config = config
        self.resources = resources


# -- plugin (server) side ----------------------------------------------------


def serve_driver(driver: TaskDriver, stdin=None, stdout=None) -> None:
    """Serve one driver over stdio until EOF/shutdown. Run via
    ``python -m nomad_tpu.client.plugin <driver_name>``."""
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    wlock = threading.Lock()

    def send(obj: dict) -> None:
        with wlock:
            stdout.write(json.dumps(obj) + "\n")
            stdout.flush()

    send(
        {
            "type": "handshake",
            "magic": PLUGIN_MAGIC,
            "version": PROTO_VERSION,
            "driver": driver.name,
            "fingerprint": bool(driver.fingerprint()),
        }
    )

    # handles live server-side; the host addresses them by wire dicts
    handles: dict[str, TaskHandle] = {}
    hlock = threading.Lock()
    shutdown = threading.Event()

    def dispatch(req: dict) -> None:
        rid = req.get("id")
        method = req.get("method")
        p = req.get("params") or {}
        try:
            if method == "fingerprint":
                result = bool(driver.fingerprint())
            elif method == "start":
                res = p.get("resources") or {}
                task = _WireTask(
                    p["task_name"],
                    driver.name,
                    p.get("config") or {},
                    _WireRes(
                        int(res.get("cpu", 0)),
                        int(res.get("memory_mb", 0)),
                    ),
                )
                h = driver.start(task, p.get("env") or {}, p["task_dir"])
                with hlock:
                    handles[h.id] = h
                result = _handle_to_wire(h)
            elif method in ("wait", "stop", "inspect", "recover"):
                wire = p["handle"]
                with hlock:
                    h = handles.get(wire["id"])
                if h is None:
                    h = _handle_from_wire(wire)
                    with hlock:
                        handles[h.id] = h
                if method == "wait":
                    code = driver.wait(h, timeout=p.get("timeout"))
                    result = {"exit_code": code, "handle": _handle_to_wire(h)}
                elif method == "stop":
                    driver.stop(h, kill_timeout=p.get("kill_timeout", 5.0))
                    result = _handle_to_wire(h)
                elif method == "recover":
                    result = {
                        "ok": bool(driver.recover(h)),
                        "handle": _handle_to_wire(h),
                    }
                else:
                    result = _handle_to_wire(driver.inspect(h))
            elif method == "shutdown":
                result = True
                shutdown.set()
            else:
                raise DriverError(f"unknown method {method!r}")
            send({"id": rid, "result": result})
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            send({"id": rid, "error": f"{type(e).__name__}: {e}"})

    for line in stdin:
        if shutdown.is_set():
            break
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
        except json.JSONDecodeError:
            continue
        # one thread per request: wait() blocks for its task's lifetime
        threading.Thread(target=dispatch, args=(req,), daemon=True).start()


# -- host (client) side ------------------------------------------------------


class PluginDriverClient(TaskDriver):
    """TaskDriver implemented by a driver plugin subprocess. Spawns the
    plugin lazily, performs the handshake, and pipelines requests."""

    def __init__(self, driver_name: str):
        self.name = driver_name
        self._proc: Optional[subprocess.Popen] = None
        self._lock = threading.Lock()
        self._pending: dict[int, threading.Event] = {}
        self._results: dict[int, dict] = {}
        self._next_id = 0
        self._fingerprint = False
        self._handshake_rest = b""

    # -- plugin lifecycle --------------------------------------------------
    def _ensure_plugin(self) -> None:
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                return
            self._proc = subprocess.Popen(
                [sys.executable, "-m", "nomad_tpu.client.plugin", self.name],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                text=True,
                env={**os.environ, "PYTHONPATH": os.pathsep.join(sys.path)},
            )
            # bounded handshake: a plugin that spawns but hangs before
            # completing its handshake LINE must not wedge every driver
            # call behind self._lock — kill it and report unhealthy. The
            # deadline covers partial lines too (a crashing child can
            # flush a truncated banner with no newline), so read raw
            # bytes under select until newline or deadline rather than
            # readline() (which would block past the first byte).
            deadline = time.monotonic() + HANDSHAKE_TIMEOUT_S
            raw_fd = self._proc.stdout.fileno()
            buf = b""
            while b"\n" not in buf:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._proc.kill()
                    self._proc.wait()
                    raise DriverError(
                        f"driver plugin {self.name!r} handshake timed "
                        f"out after {HANDSHAKE_TIMEOUT_S}s"
                    )
                ready, _, _ = select.select([raw_fd], [], [], remaining)
                if not ready:
                    continue
                chunk = os.read(raw_fd, 4096)
                if not chunk:  # EOF before a full handshake line
                    break
                buf += chunk
            line, _, rest = buf.partition(b"\n")
            # hand any over-read bytes back ahead of the reader thread's
            # stream (requests are dispatched only after the handshake,
            # so over-read can only happen from a misbehaving plugin —
            # push it through the same JSON-line parser for symmetry)
            self._handshake_rest = rest
            line = line.decode("utf-8", "replace")
            if not line.strip():
                # plugin died before the handshake (import failure etc.)
                self._proc.kill()
                raise DriverError(
                    f"driver plugin {self.name!r} exited before handshake"
                )
            hs = json.loads(line)
            if (
                hs.get("magic") != PLUGIN_MAGIC
                or hs.get("version") != PROTO_VERSION
            ):
                self._proc.kill()
                raise DriverError(
                    f"driver plugin handshake failed: {hs!r}"
                )
            self._fingerprint = bool(hs.get("fingerprint"))
            t = threading.Thread(
                target=self._read_loop,
                args=(self._proc, self._handshake_rest),
                daemon=True,
            )
            t.start()

    def _read_loop(self, proc: subprocess.Popen, rest: bytes = b"") -> None:
        import itertools

        # bytes over-read past the handshake newline bypass the buffered
        # stream — feed them through the same line parser first
        head = (
            [ln + "\n" for ln in rest.decode("utf-8", "replace").split("\n") if ln]
            if rest
            else []
        )
        for line in itertools.chain(head, proc.stdout):
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                continue
            rid = msg.get("id")
            with self._lock:
                entry = self._pending.pop(rid, None)
                if entry is not None:
                    self._results[rid] = msg
            if entry is not None:
                entry[0].set()
        # this plugin died: fail only the requests issued to IT — a
        # respawned plugin's in-flight requests must survive
        with self._lock:
            dead = [
                (rid, evt)
                for rid, (evt, p) in self._pending.items()
                if p is proc
            ]
            for rid, evt in dead:
                self._pending.pop(rid, None)
                self._results[rid] = {
                    "id": rid, "error": "driver plugin exited"
                }
            for _rid, evt in dead:
                evt.set()

    def _call(self, method: str, params: dict, timeout: Optional[float] = None):
        self._ensure_plugin()
        with self._lock:
            self._next_id += 1
            rid = self._next_id
            evt = threading.Event()
            self._pending[rid] = (evt, self._proc)
            try:
                self._proc.stdin.write(
                    json.dumps({"id": rid, "method": method, "params": params})
                    + "\n"
                )
                self._proc.stdin.flush()
            except (BrokenPipeError, OSError) as e:
                self._pending.pop(rid, None)
                raise DriverError(f"driver plugin unreachable: {e}") from e
        if not evt.wait(timeout):
            with self._lock:
                self._pending.pop(rid, None)
            return None  # caller-visible timeout (wait() contract)
        with self._lock:
            msg = self._results.pop(rid)
        if "error" in msg:
            raise DriverError(msg["error"])
        return msg["result"]

    def close(self) -> None:
        with self._lock:
            proc = self._proc
            self._proc = None
        if proc is not None and proc.poll() is None:
            try:
                proc.stdin.write(
                    json.dumps({"id": 0, "method": "shutdown", "params": {}})
                    + "\n"
                )
                proc.stdin.flush()
                # EOF releases serve_driver's stdin loop so the graceful
                # path actually completes (the loop only re-checks the
                # shutdown flag on its next line otherwise)
                proc.stdin.close()
            except (BrokenPipeError, OSError):
                pass
            try:
                proc.wait(timeout=2)
            except subprocess.TimeoutExpired:
                proc.kill()

    # -- TaskDriver contract ----------------------------------------------
    def fingerprint(self) -> bool:
        try:
            self._ensure_plugin()
        except (DriverError, OSError, ValueError):
            # ValueError covers a garbled handshake (JSONDecodeError):
            # an unhealthy plugin is an unhealthy driver, not a crash
            return False
        return self._fingerprint

    def start(self, task, env, task_dir) -> TaskHandle:
        res = getattr(task, "resources", None)
        result = self._call(
            "start",
            {
                "task_name": task.name,
                "config": dict(task.config or {}),
                "env": dict(env),
                "task_dir": task_dir,
                "resources": {
                    "cpu": getattr(res, "cpu", 0) or 0,
                    "memory_mb": getattr(res, "memory_mb", 0) or 0,
                }
                if res is not None
                else {},
            },
        )
        return _handle_from_wire(result)

    def wait(self, handle, timeout=None):
        # no host-side deadline beyond the caller's: the plugin blocks
        result = self._call(
            "wait",
            {"handle": _handle_to_wire(handle), "timeout": timeout},
            timeout=None if timeout is None else timeout + 5.0,
        )
        if result is None:
            return None
        fresh = result["handle"]
        handle.state = fresh["state"]
        handle.exit_code = fresh["exit_code"]
        handle.completed_at = fresh["completed_at"]
        return result["exit_code"]

    def stop(self, handle, kill_timeout=5.0):
        self._call(
            "stop",
            {"handle": _handle_to_wire(handle), "kill_timeout": kill_timeout},
            timeout=kill_timeout + 10.0,
        )

    def recover(self, handle: TaskHandle) -> bool:
        try:
            result = self._call(
                "recover", {"handle": _handle_to_wire(handle)}, timeout=10.0
            )
        except DriverError:
            return False
        if not result or not result.get("ok"):
            return False
        handle.meta.update(result["handle"].get("meta") or {})
        return True


def plugin_drivers(names=("raw_exec", "exec", "mock_driver")) -> dict:
    """Out-of-process driver catalog — one plugin subprocess per driver,
    spawned lazily (helper/pluginutils/catalog with external plugins)."""
    return {n: PluginDriverClient(n) for n in names}


def _main() -> None:
    from .drivers import builtin_drivers

    name = sys.argv[1] if len(sys.argv) > 1 else "raw_exec"
    catalog = builtin_drivers()
    driver = catalog.get(name)
    if driver is None:
        print(f"unknown driver {name!r}", file=sys.stderr)
        raise SystemExit(2)
    serve_driver(driver)


if __name__ == "__main__":
    _main()
