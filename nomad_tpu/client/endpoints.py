"""Client-served RPC endpoints: filesystem and log access for allocs.

Reference: client/fs_endpoint.go (FileSystem.List/Stat/Stream/Logs served
BY the client over streaming RPC; the server/HTTP agent proxies to the
node that runs the alloc, command/agent/fs_endpoint.go). The client runs a
small RPC server and advertises its address as a node attribute — the
reachability contract client/rpc.go establishes via server-mediated
connections.
"""

from __future__ import annotations

import os
import time
from typing import Iterator

from ..rpc import RPCServer

ATTR_RPC_ADDR = "nomad.client.rpc_addr"

LOG_CHUNK = 64 << 10
FOLLOW_POLL = 0.2


class ClientEndpoints:
    def __init__(self, client):
        self.client = client
        self.rpc = RPCServer()

    def start(self) -> str:
        self.rpc.start()
        self.rpc.register("FS.list", self.fs_list)
        self.rpc.register("FS.stat", self.fs_stat)
        self.rpc.register("FS.read", self.fs_read)
        self.rpc.register("FS.logs", self.fs_logs)
        return self.rpc.address

    def stop(self) -> None:
        self.rpc.stop()

    # -- helpers -----------------------------------------------------------
    def _alloc_dir(self, alloc_id: str) -> str:
        return os.path.join(self.client.data_dir, "allocs", alloc_id)

    def _resolve(self, alloc_id: str, path: str) -> str:
        """Path confined to the alloc dir (fs_endpoint.go path escaping
        guard): a crafted ../ must not escape into the client host."""
        base = os.path.realpath(self._alloc_dir(alloc_id))
        full = os.path.realpath(os.path.join(base, path.lstrip("/")))
        if full != base and not full.startswith(base + os.sep):
            raise PermissionError(f"path escapes alloc dir: {path}")
        return full

    # -- handlers ----------------------------------------------------------
    def fs_list(self, args) -> list[dict]:
        full = self._resolve(args["alloc_id"], args.get("path", "/"))
        out = []
        for name in sorted(os.listdir(full)):
            p = os.path.join(full, name)
            st = os.stat(p)
            out.append(
                {
                    "name": name,
                    "is_dir": os.path.isdir(p),
                    "size": st.st_size,
                    "mtime": st.st_mtime,
                }
            )
        return out

    def fs_stat(self, args) -> dict:
        full = self._resolve(args["alloc_id"], args.get("path", "/"))
        st = os.stat(full)
        return {
            "name": os.path.basename(full) or "/",
            "is_dir": os.path.isdir(full),
            "size": st.st_size,
            "mtime": st.st_mtime,
        }

    def fs_read(self, args) -> bytes:
        full = self._resolve(args["alloc_id"], args["path"])
        offset = int(args.get("offset", 0))
        limit = int(args.get("limit", 1 << 20))
        with open(full, "rb") as f:
            if offset < 0:  # tail semantics
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size + offset))
            else:
                f.seek(offset)
            return f.read(limit)

    def fs_logs(self, args) -> Iterator[dict]:
        """Streaming log reader; with follow=True keeps tailing until the
        connection drops (command/agent/fs_endpoint.go Logs)."""
        alloc_id = args["alloc_id"]
        task = args["task"]
        kind = args.get("type", "stdout")
        if kind not in ("stdout", "stderr"):
            raise ValueError("type must be stdout|stderr")
        path = self._resolve(alloc_id, f"{task}/{task}.{kind}")
        follow = bool(args.get("follow", False))
        offset = int(args.get("offset", 0))
        # wait briefly for the file to appear (task may be starting)
        deadline = time.time() + 5
        while not os.path.exists(path) and time.time() < deadline:
            time.sleep(0.1)
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            if offset < 0:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() + offset))
            else:
                f.seek(offset)
            idle_rounds = 0
            while True:
                chunk = f.read(LOG_CHUNK)
                if chunk:
                    idle_rounds = 0
                    yield {
                        "offset": f.tell() - len(chunk),
                        "data": chunk.decode("utf-8", "replace"),
                    }
                    continue
                if not follow:
                    return
                # logmon copy-truncate rotation shrinks the live file
                # under us: a reader offset past the new EOF would read
                # b'' forever — rewind on truncation
                try:
                    if os.fstat(f.fileno()).st_size < f.tell():
                        f.seek(0)
                        continue
                except OSError:
                    return
                # stop following once the task is dead and drained
                runner = self.client.runners.get(alloc_id)
                tr = runner.task_runners.get(task) if runner else None
                if tr is None or tr.state.state == "dead":
                    idle_rounds += 1
                    if idle_rounds > 3:
                        return
                time.sleep(FOLLOW_POLL)
