"""Per-kernel circuit breaker: closed → open → half-open.

Every ``traced_jit`` device kernel owns one breaker, keyed by its trace
name. Repeated exceptions (``failure_threshold`` consecutive) or a
single watchdog timeout trip it; while open, the kernel wrapper routes
calls to the eager reference path (the original un-jitted function, op
by op on the CPU backend) so scheduling continues with byte-identical
placement semantics. After a seeded-jitter exponential backoff one
probe call is let through half-open: success closes the breaker,
failure re-opens it with doubled backoff.

The jitter is deterministic — ``random.Random(f"{name}:{trips}")`` — so
a chaos run's recovery timing is a function of the seed-driven fault
order, not of process entropy. Registry-level ``set_forced_open`` is
the bench/degraded-mode override: it makes every ``allow()`` return
False without touching per-breaker state.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, Optional

from ..utils.metrics import global_metrics

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class CircuitBreaker:
    """One kernel's degradation state. All transitions hold ``_lock``;
    ``allow``/``record_*`` are called from the kernel hot path, so the
    closed-state fast path is one lock acquire and two reads."""

    def __init__(
        self,
        name: str,
        *,
        failure_threshold: int = 3,
        execute_deadline: float = 5.0,
        compile_deadline: float = 60.0,
        backoff_base: float = 1.0,
        backoff_cap: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self.failure_threshold = failure_threshold
        self.execute_deadline = execute_deadline
        self.compile_deadline = compile_deadline
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._trips = 0
        self._reopens = 0  # trips without an intervening close
        self._probe_at = 0.0
        self._probing = False
        self._backoff_s = 0.0
        self.last_error = ""
        self.last_trip_unix = 0.0

    # -- hot path ------------------------------------------------------------

    def allow(self) -> bool:
        """True = run the device kernel; False = take the fallback path.
        While open, exactly one caller is admitted half-open once the
        probe backoff elapses; concurrent callers stay on fallback."""
        if _FORCED_OPEN.is_set():
            return False
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN and self._clock() >= self._probe_at:
                self._set_state(HALF_OPEN)
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._reopens = 0
                self._set_state(CLOSED)

    def record_failure(self, error: Optional[BaseException] = None) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if error is not None:
                self.last_error = repr(error)
            if self._state == HALF_OPEN:
                self._trip_locked("probe failure")
            elif (
                self._state == CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._trip_locked(
                    f"{self._consecutive_failures} consecutive failures"
                )

    def record_timeout(self, error: Optional[BaseException] = None) -> None:
        """A deadline blow-out trips immediately — a hung device does
        not get ``failure_threshold`` more chances to hang siblings."""
        with self._lock:
            if error is not None:
                self.last_error = repr(error)
            if self._state != OPEN:
                self._trip_locked("watchdog timeout")

    # -- manual overrides ----------------------------------------------------

    def force_open(self) -> None:
        with self._lock:
            if self._state != OPEN:
                self._trip_locked("forced open")
            # never probe out of a manual open on its own
            self._probe_at = float("inf")

    def force_closed(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probing = False
            self._reopens = 0
            if self._state != CLOSED:
                self._set_state(CLOSED)

    # -- internals -----------------------------------------------------------

    def _trip_locked(self, reason: str) -> None:
        self._trips += 1
        self._reopens += 1
        self._probing = False
        self._consecutive_failures = 0
        raw = min(
            self.backoff_cap,
            self.backoff_base * (2.0 ** (self._reopens - 1)),
        )
        jitter = random.Random(f"{self.name}:{self._trips}").uniform(0.5, 1.5)
        self._backoff_s = raw * jitter
        self._probe_at = self._clock() + self._backoff_s
        self.last_trip_unix = time.time()
        self._set_state(OPEN)
        global_metrics.incr("nomad.resilience.trips_total")
        try:
            from ..obs.recorder import flight_recorder

            flight_recorder.record_error(
                "resilience",
                f"breaker {self.name} tripped ({reason}); "
                f"probe in {self._backoff_s:.2f}s; "
                f"last_error={self.last_error or 'n/a'}",
            )
        except Exception:
            pass

    def _set_state(self, state: str) -> None:
        self._state = state
        global_metrics.set_gauge(
            f"nomad.resilience.breaker_state.{self.name}",
            _STATE_GAUGE[state],
        )

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "trips": self._trips,
                "consecutive_failures": self._consecutive_failures,
                "backoff_s": round(self._backoff_s, 4),
                "probe_in_s": (
                    round(max(0.0, self._probe_at - self._clock()), 4)
                    if self._state == OPEN and self._probe_at != float("inf")
                    else 0.0
                ),
                "execute_deadline_s": self.execute_deadline,
                "compile_deadline_s": self.compile_deadline,
                "failure_threshold": self.failure_threshold,
                "last_error": self.last_error,
                "last_trip_unix": self.last_trip_unix,
            }


# -- registry ----------------------------------------------------------------

_REG_LOCK = threading.Lock()
_BREAKERS: Dict[str, CircuitBreaker] = {}
_FORCED_OPEN = threading.Event()
_TUNABLES = (
    "failure_threshold",
    "execute_deadline",
    "compile_deadline",
    "backoff_base",
    "backoff_cap",
)
# None = "no env pin": the deadline defaults then come from the
# calibration table (obs/calibrate.py, resilience.execute_deadline_s /
# compile_deadline_s) so they carry provenance. Env vars keep
# precedence, and configure() overrides both.
_DEFAULTS: dict = {
    "failure_threshold": _env_int("NOMAD_TPU_BREAKER_THRESHOLD", 3),
    "execute_deadline": _env_float("NOMAD_TPU_KERNEL_EXECUTE_DEADLINE", None),
    "compile_deadline": _env_float("NOMAD_TPU_KERNEL_COMPILE_DEADLINE", None),
    "backoff_base": _env_float("NOMAD_TPU_BREAKER_BACKOFF", 1.0),
    "backoff_cap": _env_float("NOMAD_TPU_BREAKER_BACKOFF_CAP", 30.0),
}


def _resolved_defaults() -> dict:
    """Concrete constructor kwargs: env-pinned / configure()d values win;
    an unpinned deadline reads the calibration table at construction
    time (lazy import — same cycle workaround as server/admission.py)."""
    out = dict(_DEFAULTS)
    if out["execute_deadline"] is None or out["compile_deadline"] is None:
        from ..obs.calibrate import global_table

        tbl = global_table.breaker_defaults()
        if out["execute_deadline"] is None:
            out["execute_deadline"] = tbl["execute_deadline"]
        if out["compile_deadline"] is None:
            out["compile_deadline"] = tbl["compile_deadline"]
    return out


def breaker_for(name: str) -> CircuitBreaker:
    with _REG_LOCK:
        br = _BREAKERS.get(name)
        if br is None:
            br = CircuitBreaker(name, **_resolved_defaults())
            _BREAKERS[name] = br
        return br


def all_breakers() -> Dict[str, CircuitBreaker]:
    with _REG_LOCK:
        return dict(_BREAKERS)


def snapshot_all() -> Dict[str, dict]:
    return {name: br.snapshot() for name, br in all_breakers().items()}


def configure(**overrides) -> dict:
    """Override registry defaults (and push tunables onto live breakers
    — the chaos runner shortens deadlines for kernels that already
    traced). Returns the previous defaults so callers can restore:
    ``prev = configure(execute_deadline=0.1); ...; configure(**prev)``.
    """
    with _REG_LOCK:
        prev = dict(_DEFAULTS)
        for key, value in overrides.items():
            if key not in _DEFAULTS:
                raise TypeError(f"unknown breaker tunable: {key}")
            _DEFAULTS[key] = value
        resolved = _resolved_defaults()
        for br in _BREAKERS.values():
            for key in _TUNABLES:
                setattr(br, key, resolved[key])
        return prev


def reset_all() -> None:
    """Drop every breaker (fresh closed state on next ``breaker_for``)
    and clear the forced-open override. Test/chaos-run hygiene."""
    with _REG_LOCK:
        _BREAKERS.clear()
    _FORCED_OPEN.clear()


def set_forced_open(flag: bool) -> None:
    """Registry-wide degraded-mode switch: every ``allow()`` returns
    False while set. Used by the bench ``degraded_mode`` block and the
    byte-identity tests to force the pure reference path."""
    if flag:
        _FORCED_OPEN.set()
    else:
        _FORCED_OPEN.clear()


def forced_open() -> bool:
    return _FORCED_OPEN.is_set()


def degraded() -> bool:
    """True when any kernel is off the device path — forced open, or at
    least one breaker not closed. Cheap enough for once-per-pass use."""
    if _FORCED_OPEN.is_set():
        return True
    with _REG_LOCK:
        return any(br._state != CLOSED for br in _BREAKERS.values())
