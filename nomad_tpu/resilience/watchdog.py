"""Deadline executor: run a thunk on a reusable watchdog thread and
abandon it if it blows its deadline.

Python cannot kill a thread stuck inside a C extension (a hung PJRT
call never re-enters the interpreter), so on timeout the worker thread
is *poisoned*: the caller marks the job abandoned under its lock and
raises; when (if) the stuck call ever returns, the worker sees the
abandoned flag, discards the result, and exits instead of rejoining
the pool. A fresh worker is spawned for the next call. The happy path
reuses one idle thread per concurrency level — a queue hand-off and an
Event wait per kernel call, well under the ≤1% bench overhead budget.

The two-stage deadline mirrors compile-vs-execute reality: the caller
waits ``deadline_s`` first; if the job is still running but
``extend_probe()`` says a trace actually started (a retrace means XLA
compilation, legitimately slow), the wait extends to
``extend_deadline_s`` total before declaring a timeout.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional

from .errors import KernelDeadlineExceeded

_MAX_IDLE = 8


class _Job:
    __slots__ = ("thunk", "done", "lock", "abandoned", "result", "error")

    def __init__(self, thunk: Callable[[], Any]):
        self.thunk = thunk
        self.done = threading.Event()
        self.lock = threading.Lock()
        self.abandoned = False
        self.result: Any = None
        self.error: Optional[BaseException] = None


class _Worker(threading.Thread):
    def __init__(self, pool: "DeadlineExecutor", n: int):
        super().__init__(name=f"kernel-watchdog-{n}", daemon=True)
        self.pool = pool
        self.inbox: "queue.Queue[Optional[_Job]]" = queue.Queue(maxsize=1)

    def run(self) -> None:
        while True:
            job = self.inbox.get()
            if job is None:
                return
            try:
                result = job.thunk()
                error: Optional[BaseException] = None
            except BaseException as e:  # re-raised in the caller thread
                result, error = None, e
            with job.lock:
                if job.abandoned:
                    # timed out: the caller already raised and moved to
                    # the fallback path — discard and die poisoned
                    return
                job.result, job.error = result, error
                job.done.set()
            self.pool._release(self)


class DeadlineExecutor:
    """Pool of watchdog threads, one in flight per concurrent caller."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._free: list[_Worker] = []
        self._spawned = 0
        self.poisoned = 0

    @property
    def spawned(self) -> int:
        with self._lock:
            return self._spawned

    def _acquire(self) -> _Worker:
        with self._lock:
            if self._free:
                return self._free.pop()
            self._spawned += 1
            w = _Worker(self, self._spawned)
        w.start()
        return w

    def _release(self, w: _Worker) -> None:
        with self._lock:
            if len(self._free) < _MAX_IDLE:
                self._free.append(w)
                return
        w.inbox.put(None)  # surplus: let the thread exit

    def run(
        self,
        thunk: Callable[[], Any],
        *,
        name: str,
        deadline_s: float,
        extend_deadline_s: Optional[float] = None,
        extend_probe: Optional[Callable[[], bool]] = None,
    ) -> Any:
        w = self._acquire()
        job = _Job(thunk)
        w.inbox.put(job)
        phase = "execute"
        finished = job.done.wait(deadline_s)
        if (
            not finished
            and extend_probe is not None
            and extend_deadline_s is not None
            and extend_deadline_s > deadline_s
            and extend_probe()
        ):
            phase = "compile"
            finished = job.done.wait(extend_deadline_s - deadline_s)
        if not finished:
            with job.lock:
                if not job.done.is_set():
                    job.abandoned = True
            if job.abandoned:
                with self._lock:
                    self.poisoned += 1
                deadline = (
                    extend_deadline_s if phase == "compile" else deadline_s
                )
                raise KernelDeadlineExceeded(name, deadline, phase)
        if job.error is not None:
            raise job.error
        return job.result


global_executor = DeadlineExecutor()
