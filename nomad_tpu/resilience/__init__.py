"""nomad_tpu.resilience — unified degradation layer.

Three surfaces keep the scheduler placing allocations when the device
backend, the transport, or a single pass misbehaves:

- :mod:`breaker` — per-kernel circuit breakers with watchdog deadlines;
  a tripped kernel transparently runs on the eager CPU/reference path.
- :mod:`watchdog` — the deadline executor behind the breaker (poisoned
  worker threads, compile-aware two-stage deadlines).
- eval-lifecycle deadlines + RPC retry/backoff live at their call
  sites (``server/worker.py``, ``rpc/client.py``) and share the
  exception types in :mod:`errors`.

Obs surface: ``nomad.resilience.breaker_state.<kernel>`` gauges,
``trips_total``, ``fallback_calls``, ``fallback_passes``,
``rpc.retries``, ``eval.deadline_nacks`` counters; breaker trips land
in the flight recorder (``nomad-tpu resilience status``).
"""

from .breaker import (
    CircuitBreaker,
    all_breakers,
    breaker_for,
    configure,
    degraded,
    forced_open,
    reset_all,
    set_forced_open,
    snapshot_all,
)
from .errors import EvalDeadlineExceeded, KernelDeadlineExceeded
from .watchdog import DeadlineExecutor, global_executor

__all__ = [
    "CircuitBreaker",
    "DeadlineExecutor",
    "EvalDeadlineExceeded",
    "KernelDeadlineExceeded",
    "all_breakers",
    "breaker_for",
    "configure",
    "degraded",
    "forced_open",
    "global_executor",
    "reset_all",
    "set_forced_open",
    "snapshot_all",
]
