"""Deadline exceptions shared across the resilience layer.

Both are plain ``Exception`` subclasses on purpose: they must be
catchable by the worker's generic recovery handlers (unlike
``ChaosThreadKill``, which models a crash and derives from
``BaseException`` so those handlers can NOT absorb it).
"""

from __future__ import annotations


class KernelDeadlineExceeded(RuntimeError):
    """A device kernel blew through its watchdog deadline. The call may
    still be running on an abandoned worker thread — the result, if it
    ever arrives, is discarded."""

    def __init__(self, name: str, deadline_s: float, phase: str = "execute"):
        self.kernel = name
        self.deadline_s = deadline_s
        self.phase = phase
        super().__init__(
            f"kernel {name} exceeded {deadline_s:.3f}s {phase} deadline"
        )


class EvalDeadlineExceeded(RuntimeError):
    """An evaluation's per-processing-pass deadline expired in the
    worker. The eval is nacked with escalating delay (attempt count
    carried on the eval) rather than held forever."""

    def __init__(self, eval_id: str, deadline_s: float, attempts: int = 0):
        self.eval_id = eval_id
        self.deadline_s = deadline_s
        self.attempts = attempts
        super().__init__(
            f"eval {eval_id} exceeded {deadline_s:.3f}s processing deadline "
            f"(attempts={attempts})"
        )
