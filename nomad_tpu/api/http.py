"""HTTP API agent.

Reference: command/agent/http.go (:275-360 route table). The `/v1/...`
REST surface over the server, stdlib-only (ThreadingHTTPServer): jobs
(register/list/read/plan/evals/allocs/deregister), nodes (list/read/
drain/eligibility), allocations, evaluations, operator scheduler config
(the seam the TPU algorithm is toggled through,
nomad/structs/operator.go:128-169), agent self, and metrics.

Blocking queries: ``?index=N&wait=S`` holds the request until the state
store passes index N (the memdb WatchSet analog, state_store.go blocking
queries); every response carries ``X-Nomad-Index``.
"""

from __future__ import annotations

import json
import re
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..server.admission import AdmissionRejected
from ..server.fsm import MsgType
from ..structs import Evaluation, new_id
from ..structs.job import JOB_DEFAULT_PRIORITY
from .codec import _decode_into, decode_job, encode


class APIError(Exception):
    def __init__(self, status: int, message: str, headers: Optional[dict] = None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers = headers


class StreamingResponse:
    """Marker for NDJSON streaming handlers (/v1/event/stream)."""

    def __init__(self, iterator):
        self.iterator = iterator


class HTTPAgent:
    """Routes + handlers bound to a Server (and optionally a Client)."""

    def __init__(self, server, client=None, host="127.0.0.1", port=4646):
        self.server = server
        self.client = client
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.routes = [
            (re.compile(r"^/v1/jobs$"), self.handle_jobs),
            (re.compile(r"^/v1/job/(?P<job_id>[^/]+)$"), self.handle_job),
            (re.compile(r"^/v1/job/(?P<job_id>[^/]+)/plan$"), self.handle_job_plan),
            (
                re.compile(r"^/v1/job/(?P<job_id>[^/]+)/evaluations$"),
                self.handle_job_evals,
            ),
            (
                re.compile(r"^/v1/job/(?P<job_id>[^/]+)/allocations$"),
                self.handle_job_allocs,
            ),
            (
                re.compile(r"^/v1/job/(?P<job_id>[^/]+)/summary$"),
                self.handle_job_summary,
            ),
            (re.compile(r"^/v1/nodes$"), self.handle_nodes),
            (re.compile(r"^/v1/node/(?P<node_id>[^/]+)$"), self.handle_node),
            (
                re.compile(r"^/v1/node/(?P<node_id>[^/]+)/drain$"),
                self.handle_node_drain,
            ),
            (
                re.compile(r"^/v1/node/(?P<node_id>[^/]+)/eligibility$"),
                self.handle_node_eligibility,
            ),
            (
                re.compile(r"^/v1/node/(?P<node_id>[^/]+)/allocations$"),
                self.handle_node_allocs,
            ),
            (
                re.compile(r"^/v1/job/(?P<job_id>[^/]+)/deployments$"),
                self.handle_job_deployments,
            ),
            (re.compile(r"^/v1/deployments$"), self.handle_deployments),
            (
                re.compile(r"^/v1/deployment/promote/(?P<deployment_id>[^/]+)$"),
                self.handle_deployment_promote,
            ),
            (
                re.compile(r"^/v1/deployment/fail/(?P<deployment_id>[^/]+)$"),
                self.handle_deployment_fail,
            ),
            (
                re.compile(r"^/v1/deployment/pause/(?P<deployment_id>[^/]+)$"),
                self.handle_deployment_pause,
            ),
            (
                re.compile(r"^/v1/deployment/(?P<deployment_id>[^/]+)$"),
                self.handle_deployment,
            ),
            (re.compile(r"^/v1/volumes$"), self.handle_volumes),
            (
                re.compile(r"^/v1/volume/csi/(?P<volume_id>[^/]+)$"),
                self.handle_volume,
            ),
            (re.compile(r"^/v1/plugins$"), self.handle_plugins),
            (re.compile(r"^/v1/allocations$"), self.handle_allocs),
            (
                re.compile(r"^/v1/allocation/(?P<alloc_id>[^/]+)/stop$"),
                self.handle_alloc_stop,
            ),
            (
                # score provenance: why this alloc landed where it did
                # (obs/explain.py; `nomad-tpu alloc why`)
                re.compile(
                    r"^/v1/allocations?/(?P<alloc_id>[^/]+)/explain$"
                ),
                self.handle_alloc_explain,
            ),
            (
                re.compile(r"^/v1/allocation/(?P<alloc_id>[^/]+)$"),
                self.handle_alloc,
            ),
            (re.compile(r"^/v1/evaluations$"), self.handle_evals),
            (
                # per-group placement explanation for one eval (the
                # flight recorder's explanation ring, obs/recorder.py)
                re.compile(
                    r"^/v1/evaluations?/(?P<eval_id>[^/]+)/placement$"
                ),
                self.handle_eval_placement,
            ),
            (
                re.compile(r"^/v1/evaluation/(?P<eval_id>[^/]+)$"),
                self.handle_eval,
            ),
            (
                re.compile(r"^/v1/operator/scheduler/configuration$"),
                self.handle_scheduler_config,
            ),
            (
                # heterogeneity observability: which device classes hold
                # which jobs' allocations (scheduler/hetero.py)
                re.compile(r"^/v1/operator/scheduler/placements$"),
                self.handle_hetero_placements,
            ),
            (
                # raft inspection (command/operator_raft_list.go,
                # nomad/operator_endpoint.go RaftGetConfiguration)
                re.compile(r"^/v1/operator/raft/configuration$"),
                self.handle_raft_configuration,
            ),
            (
                # peer removal (command/operator_raft_remove.go,
                # operator_endpoint.go RaftRemovePeerByID)
                re.compile(r"^/v1/operator/raft/peer$"),
                self.handle_raft_peer,
            ),
            (
                # continuous-defrag control plane (server/defrag.py):
                # GET status/counters, POST an immediate cycle
                re.compile(r"^/v1/operator/defrag$"),
                self.handle_operator_defrag,
            ),
            (
                re.compile(r"^/v1/job/(?P<job_id>[^/]+)/dispatch$"),
                self.handle_job_dispatch,
            ),
            (
                # version history (job_endpoint.go GetJobVersions)
                re.compile(r"^/v1/job/(?P<job_id>[^/]+)/versions$"),
                self.handle_job_versions,
            ),
            (
                # rollback to a prior version (job_endpoint.go Revert)
                re.compile(r"^/v1/job/(?P<job_id>[^/]+)/revert$"),
                self.handle_job_revert,
            ),
            (
                # forced re-evaluation (job_endpoint.go Evaluate)
                re.compile(r"^/v1/job/(?P<job_id>[^/]+)/evaluate$"),
                self.handle_job_evaluate,
            ),
            (
                # manual GC sweep (system_endpoint.go GarbageCollect)
                re.compile(r"^/v1/system/gc$"),
                self.handle_system_gc,
            ),
            (
                re.compile(r"^/v1/job/(?P<job_id>[^/]+)/periodic/force$"),
                self.handle_periodic_force,
            ),
            (re.compile(r"^/v1/event/stream$"), self.handle_event_stream),
            (re.compile(r"^/v1/namespaces$"), self.handle_namespaces),
            (
                re.compile(r"^/v1/namespace/(?P<name>[^/]+)$"),
                self.handle_namespace,
            ),
            (re.compile(r"^/v1/namespace$"), self.handle_namespace_create),
            (
                re.compile(r"^/v1/job/(?P<job_id>[^/]+)/scale$"),
                self.handle_job_scale,
            ),
            (
                re.compile(r"^/v1/scaling/policies$"),
                self.handle_scaling_policies,
            ),
            (re.compile(r"^/v1/search$"), self.handle_search),
            (
                re.compile(r"^/v1/client/fs/ls/(?P<alloc_id>[^/]+)$"),
                self.handle_fs_ls,
            ),
            (
                re.compile(r"^/v1/client/fs/cat/(?P<alloc_id>[^/]+)$"),
                self.handle_fs_cat,
            ),
            (
                re.compile(r"^/v1/client/fs/logs/(?P<alloc_id>[^/]+)$"),
                self.handle_fs_logs,
            ),
            (
                re.compile(r"^/v1/operator/snapshot/save$"),
                self.handle_snapshot_save,
            ),
            (re.compile(r"^/v1/agent/self$"), self.handle_agent_self),
            (
                # pprof surface (command/agent/http.go:331)
                re.compile(r"^/v1/agent/pprof/(?P<kind>[^/]+)$"),
                self.handle_pprof,
            ),
            (
                # operator debug bundle (command/operator_debug.go:54)
                re.compile(r"^/v1/operator/debug$"),
                self.handle_operator_debug,
            ),
            (
                # flight-recorder surface: recent traces + error events
                re.compile(r"^/v1/agent/trace$"),
                self.handle_agent_trace,
            ),
            (
                re.compile(r"^/v1/agent/trace/(?P<eval_id>[^/]+)$"),
                self.handle_agent_trace,
            ),
            (
                # resilience surface: breaker states + recent trips
                re.compile(r"^/v1/agent/resilience$"),
                self.handle_agent_resilience,
            ),
            (
                # SLO surface: windowed latency percentiles + verdict
                re.compile(r"^/v1/agent/slo$"),
                self.handle_agent_slo,
            ),
            (
                # calibration surface: constant provenance + learned
                # throughput cells
                re.compile(r"^/v1/agent/calibration$"),
                self.handle_agent_calibration,
            ),
            (re.compile(r"^/v1/status/leader$"), self.handle_leader),
            (re.compile(r"^/v1/metrics$"), self.handle_metrics),
            (re.compile(r"^/v1/acl/bootstrap$"), self.handle_acl_bootstrap),
            (re.compile(r"^/v1/acl/policies$"), self.handle_acl_policies),
            (
                re.compile(r"^/v1/acl/policy/(?P<name>[^/]+)$"),
                self.handle_acl_policy,
            ),
            (re.compile(r"^/v1/acl/tokens$"), self.handle_acl_tokens),
            (re.compile(r"^/v1/acl/token$"), self.handle_acl_token_create),
            (re.compile(r"^/v1/acl/token/self$"), self.handle_acl_token_self),
            (
                re.compile(r"^/v1/acl/token/(?P<accessor>[^/]+)$"),
                self.handle_acl_token,
            ),
        ]

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        agent = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # silence
                pass

            def _dispatch(self, method):
                parsed = urlparse(self.path)
                query = {
                    k: v[0] for k, v in parse_qs(parsed.query).items()
                }
                # token: X-Nomad-Token header wins over ?token= (http.go
                # parseToken); stashed under a reserved key for handlers
                query["_secret"] = self.headers.get(
                    "X-Nomad-Token", query.get("token", "")
                )
                body = None
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    raw = self.rfile.read(length)
                    try:
                        body = json.loads(raw)
                    except json.JSONDecodeError:
                        self._reply(400, {"error": "invalid JSON body"})
                        return
                for pattern, handler in agent.routes:
                    m = pattern.match(parsed.path)
                    if m:
                        try:
                            result = handler(
                                method, body, query, **m.groupdict()
                            )
                        except APIError as e:
                            self._reply(
                                e.status, {"error": e.message}, headers=e.headers
                            )
                        except AdmissionRejected as e:
                            # overload: the controller refused the work
                            # before anything was committed — tell the
                            # client when to come back (RFC 6585)
                            self._reply(
                                429,
                                {
                                    "error": str(e),
                                    "admission_level": e.level,
                                    "retry_after": e.retry_after,
                                },
                                headers={"Retry-After": f"{e.retry_after:g}"},
                            )
                        except Exception as e:  # noqa: BLE001
                            self._reply(500, {"error": str(e)})
                        else:
                            if isinstance(result, StreamingResponse):
                                self._stream(result.iterator)
                            else:
                                self._reply(200, result)
                        return
                self._reply(404, {"error": f"no handler for {parsed.path}"})

            def _reply(self, status, payload, headers=None):
                data = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.send_header(
                    "X-Nomad-Index", str(agent.server.store.latest_index)
                )
                for name, value in (headers or {}).items():
                    self.send_header(name, str(value))
                self.end_headers()
                self.wfile.write(data)

            def _stream(self, iterator):
                """NDJSON chunked streaming (nomad/stream/ndjson.go)."""
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def write_chunk(data: bytes):
                    self.wfile.write(f"{len(data):x}\r\n".encode())
                    self.wfile.write(data + b"\r\n")
                    self.wfile.flush()

                try:
                    for line in iterator:
                        write_chunk(line.encode() + b"\n")
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="http-agent", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2)

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- blocking-query helper --------------------------------------------
    def _maybe_block(self, query) -> None:
        index = int(query.get("index", 0) or 0)
        if index:
            wait = float(query.get("wait", 5.0) or 5.0)
            self.server.store.wait_for_index(index + 1, timeout=wait)

    # -- ACL enforcement ---------------------------------------------------
    def _acl(self, query):
        """Resolve the request token to a compiled ACL; None when ACLs are
        disabled (reference: agent http.go parseToken + srv.ResolveToken)."""
        from ..server.acl import TokenError

        try:
            return self.server.acl.resolve_token(query.get("_secret", ""))
        except TokenError as e:
            raise APIError(403, str(e)) from None

    def _enforce_ns(self, query, cap: str) -> None:
        acl = self._acl(query)
        ns = query.get("namespace", "default")
        if acl is not None and not acl.allow_namespace_operation(ns, cap):
            raise APIError(403, "Permission denied")

    def _enforce(self, query, check: str) -> None:
        """check: '<scope>_<read|write|list>' e.g. 'node_write'."""
        acl = self._acl(query)
        if acl is None:
            return
        if not getattr(acl, f"allow_{check}")():
            raise APIError(403, "Permission denied")

    def _enforce_management(self, query) -> None:
        acl = self._acl(query)
        if acl is not None and not acl.is_management():
            raise APIError(403, "Permission denied")

    def _enforce_obj_ns(self, query, namespace: str, cap: str) -> None:
        """Enforce against an object's OWN namespace (not the query param)
        — the reference resolves the object first, then checks its
        namespace (e.g. deployment_endpoint.go)."""
        acl = self._acl(query)
        if acl is not None and not acl.allow_namespace_operation(namespace, cap):
            raise APIError(403, "Permission denied")

    def _ns_filter(self, query, cap: str):
        """Returns a predicate filtering objects to namespaces the token
        can see (list endpoints must not leak other namespaces)."""
        acl = self._acl(query)
        if acl is None:
            return lambda ns: True
        return lambda ns: acl.allow_namespace_operation(ns, cap)

    # -- handlers ----------------------------------------------------------
    def handle_jobs(self, method, body, query):
        if method == "GET":
            self._enforce_ns(query, "list-jobs")
            visible = self._ns_filter(query, "list-jobs")
            self._maybe_block(query)
            return [
                {
                    "id": j.id,
                    "name": j.name,
                    "namespace": j.namespace,
                    "type": j.type,
                    "priority": j.priority,
                    "status": j.status,
                    "stop": j.stop,
                    "version": j.version,
                    "modify_index": j.modify_index,
                }
                for j in self.server.store.jobs()
                if visible(j.namespace)
            ]
        if method in ("POST", "PUT"):
            payload = body.get("job") if isinstance(body, dict) else None
            if payload is None:
                raise APIError(400, "missing 'job' in body")
            job = decode_job(payload)
            self._enforce_obj_ns(query, job.namespace or "default", "submit-job")
            if not job.id:
                raise APIError(400, "job id is required")
            if not job.task_groups:
                raise APIError(400, "job needs at least one task group")
            job.priority = job.priority or JOB_DEFAULT_PRIORITY
            try:
                ev = self.server.register_job(job)
            except ValueError as e:  # JobValidationError
                raise APIError(400, str(e)) from None
            return {"eval_id": ev.id, "job_modify_index": job.modify_index}
        raise APIError(405, f"method {method} not allowed")

    def _get_job(self, job_id, query):
        ns = query.get("namespace", "default")
        job = self.server.store.job_by_id(ns, job_id)
        if job is None:
            raise APIError(404, f"job {job_id} not found")
        return job

    def handle_job(self, method, body, query, job_id):
        if method == "GET":
            self._enforce_ns(query, "read-job")
            self._maybe_block(query)
            return encode(self._get_job(job_id, query))
        if method == "DELETE":
            self._enforce_ns(query, "submit-job")
            job = self._get_job(job_id, query)
            ev = self.server.deregister_job(job.namespace, job.id)
            return {"eval_id": ev.id if ev else ""}
        raise APIError(405, f"method {method} not allowed")

    def handle_job_plan(self, method, body, query, job_id):
        """Dry-run: run the scheduler inline on a snapshot without
        submitting the plan (SURVEY.md §3.3, nomad/job_endpoint Job.Plan)."""
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        payload = body.get("job") if isinstance(body, dict) else None
        if payload is None:
            raise APIError(400, "missing 'job' in body")
        job = decode_job(payload)
        self._enforce_obj_ns(query, job.namespace or "default", "submit-job")
        from ..scheduler.annotate import plan_job

        return plan_job(self.server.store, job)

    def handle_job_evals(self, method, body, query, job_id):
        self._enforce_ns(query, "read-job")
        job = self._get_job(job_id, query)
        return [encode(e) for e in self.server.store.evals_by_job(job.namespace, job.id)]

    def handle_job_allocs(self, method, body, query, job_id):
        self._enforce_ns(query, "read-job")
        job = self._get_job(job_id, query)
        self._maybe_block(query)
        return [
            encode(a)
            for a in self.server.store.allocs_by_job(job.namespace, job.id)
        ]

    def handle_job_summary(self, method, body, query, job_id):
        self._enforce_ns(query, "read-job")
        job = self._get_job(job_id, query)
        allocs = self.server.store.allocs_by_job(job.namespace, job.id)
        summary: dict[str, dict[str, int]] = {}
        for tg in job.task_groups:
            summary[tg.name] = {
                "queued": 0, "starting": 0, "running": 0,
                "complete": 0, "failed": 0, "lost": 0,
            }
        for a in allocs:
            s = summary.setdefault(a.task_group, {})
            key = {
                "pending": "starting",
                "running": "running",
                "complete": "complete",
                "failed": "failed",
                "lost": "lost",
            }.get(a.client_status, "starting")
            if a.desired_status == "run" or a.client_terminal_status():
                s[key] = s.get(key, 0) + 1
        for ev in self.server.store.evals_by_job(job.namespace, job.id):
            for tg, n in ev.queued_allocations.items():
                if tg in summary:
                    summary[tg]["queued"] = max(summary[tg]["queued"], n)
        return {"job_id": job.id, "summary": summary}

    def handle_job_deployments(self, method, body, query, job_id):
        self._enforce_ns(query, "read-job")
        job = self._get_job(job_id, query)
        return [
            encode(d)
            for d in self.server.store.deployments()
            if d.job_id == job.id and d.namespace == job.namespace
        ]

    def handle_deployments(self, method, body, query):
        self._enforce_ns(query, "read-job")
        visible = self._ns_filter(query, "read-job")
        self._maybe_block(query)
        return [
            encode(d)
            for d in self.server.store.deployments()
            if visible(d.namespace)
        ]

    def _get_deployment(self, deployment_id):
        d = self.server.store.deployment_by_id(deployment_id)
        if d is None:
            matches = [
                x
                for x in self.server.store.deployments()
                if x.id.startswith(deployment_id)
            ]
            if len(matches) != 1:
                raise APIError(404, f"deployment {deployment_id} not found")
            d = matches[0]
        return d

    def handle_deployment(self, method, body, query, deployment_id):
        d = self._get_deployment(deployment_id)
        self._enforce_obj_ns(query, d.namespace, "read-job")
        return encode(d)

    def handle_deployment_promote(self, method, body, query, deployment_id):
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        d = self._get_deployment(deployment_id)
        self._enforce_obj_ns(query, d.namespace, "submit-job")
        ok = self.server.deployment_watcher.promote(d.id)
        if not ok:
            raise APIError(400, "deployment is not active")
        return {"promoted": True}

    def handle_deployment_pause(self, method, body, query, deployment_id):
        """POST /v1/deployment/pause/:id {"pause": bool}
        (deployment_endpoint.go Pause)."""
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        d = self._get_deployment(deployment_id)
        self._enforce_obj_ns(query, d.namespace, "submit-job")
        pause = bool((body or {}).get("pause", True))
        ok = self.server.deployment_watcher.pause(d.id, pause)
        if not ok:
            raise APIError(400, "deployment is not active")
        return {"paused": pause}

    def handle_deployment_fail(self, method, body, query, deployment_id):
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        d = self._get_deployment(deployment_id)
        self._enforce_obj_ns(query, d.namespace, "submit-job")
        ok = self.server.deployment_watcher.fail(d.id)
        if not ok:
            raise APIError(400, "deployment is not active")
        return {"failed": True}

    def handle_volumes(self, method, body, query):
        """GET /v1/volumes — CSI volume stubs (csi_endpoint.go List)."""
        if method != "GET":
            raise APIError(405, "method not allowed")
        self._enforce_ns(query, "csi-list-volume")
        visible = self._ns_filter(query, "csi-list-volume")
        self._maybe_block(query)
        return [
            {
                "id": v.id,
                "namespace": v.namespace,
                "name": v.name,
                "plugin_id": v.plugin_id,
                "access_mode": v.access_mode,
                "attachment_mode": v.attachment_mode,
                "schedulable": v.schedulable,
                "claims_read": len(v.read_claims),
                "claims_write": len(v.write_claims),
                "modify_index": v.modify_index,
            }
            for v in self.server.store.csi_volumes()
            if visible(v.namespace)
        ]

    def handle_volume(self, method, body, query, volume_id):
        """GET/PUT/DELETE /v1/volume/csi/:id (csi_endpoint.go)."""
        from ..structs.volumes import CSIVolume

        if method == "GET":
            self._enforce_ns(query, "csi-read-volume")
            vol = self.server.store.csi_volume_by_id(volume_id)
            if vol is None:
                raise APIError(404, f"volume not found: {volume_id}")
            self._enforce_obj_ns(query, vol.namespace, "csi-read-volume")
            return encode(vol)
        if method == "PUT" or method == "POST":
            vol = _decode_into(CSIVolume, body or {})
            if vol.id and vol.id != volume_id:
                raise APIError(
                    400, f"volume id {vol.id!r} does not match URL {volume_id!r}"
                )
            vol.id = vol.id or volume_id
            # enforce against the volume's own namespace (cross-namespace
            # writes must not ride the query-param default)
            self._enforce_obj_ns(query, vol.namespace, "csi-write-volume")
            existing = self.server.store.csi_volume_by_id(vol.id)
            if existing is not None:
                self._enforce_obj_ns(
                    query, existing.namespace, "csi-write-volume"
                )
            try:
                self.server.register_csi_volume(vol)
            except ValueError as e:  # spec change on an in-use volume
                raise APIError(409, str(e)) from None
            return {"index": self.server.store.latest_index}
        if method == "DELETE":
            existing = self.server.store.csi_volume_by_id(volume_id)
            if existing is None:
                raise APIError(404, f"volume not found: {volume_id}")
            self._enforce_obj_ns(query, existing.namespace, "csi-write-volume")
            force = query.get("force", "") in ("true", "1")
            try:
                self.server.deregister_csi_volume(volume_id, force=force)
            except KeyError as e:
                raise APIError(404, str(e)) from None
            except ValueError as e:
                raise APIError(409, str(e)) from None
            return {"index": self.server.store.latest_index}
        raise APIError(405, "method not allowed")

    def handle_plugins(self, method, body, query):
        """GET /v1/plugins — derived CSI plugin health."""
        if method != "GET":
            raise APIError(405, "method not allowed")
        self._enforce(query, "plugin_list")
        return [
            {
                "id": p.id,
                "nodes_healthy": p.nodes_healthy,
                "controllers_healthy": p.controllers_healthy,
            }
            for p in self.server.store.csi_plugins().values()
        ]

    def handle_nodes(self, method, body, query):
        self._enforce(query, "node_read")
        self._maybe_block(query)
        return [
            {
                "id": n.id,
                "name": n.name,
                "datacenter": n.datacenter,
                "node_class": n.node_class,
                "device_class": n.device_class,
                "status": n.status,
                "scheduling_eligibility": n.scheduling_eligibility,
                "drain": n.drain is not None,
                "modify_index": n.modify_index,
            }
            for n in self.server.store.nodes()
        ]

    def _get_node(self, node_id):
        node = self.server.store.node_by_id(node_id)
        if node is None:
            # prefix match convenience (CLI-style short ids)
            matches = [
                n for n in self.server.store.nodes() if n.id.startswith(node_id)
            ]
            if len(matches) == 1:
                return matches[0]
            raise APIError(404, f"node {node_id} not found")
        return node

    def handle_node(self, method, body, query, node_id):
        self._enforce(query, "node_read")
        return encode(self._get_node(node_id))

    def handle_node_drain(self, method, body, query, node_id):
        self._enforce(query, "node_write")
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        node = self._get_node(node_id)
        from ..structs import DrainStrategy

        enable = bool(body.get("drain_enabled", True)) if body else True
        drain = (
            DrainStrategy(
                deadline_s=float(body.get("deadline_s", 3600)),
                ignore_system_jobs=bool(body.get("ignore_system_jobs", False)),
            )
            if enable
            else None
        )
        evals = self.server.update_node_drain(node.id, drain)
        return {"eval_ids": [e.id for e in evals]}

    def handle_node_eligibility(self, method, body, query, node_id):
        self._enforce(query, "node_write")
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        node = self._get_node(node_id)
        elig = body.get("eligibility") if body else None
        if elig not in ("eligible", "ineligible"):
            raise APIError(400, "eligibility must be eligible|ineligible")
        self.server.raft_apply(
            MsgType.NODE_ELIGIBILITY,
            {"node_id": node.id, "eligibility": elig},
        )
        return {"eligibility": elig}

    def handle_node_allocs(self, method, body, query, node_id):
        self._enforce(query, "node_read")
        node = self._get_node(node_id)
        return [encode(a) for a in self.server.store.allocs_by_node(node.id)]

    def handle_allocs(self, method, body, query):
        self._enforce_ns(query, "read-job")
        visible = self._ns_filter(query, "read-job")
        self._maybe_block(query)
        return [
            {
                "id": a.id,
                "eval_id": a.eval_id,
                "name": a.name,
                "node_id": a.node_id,
                "job_id": a.job_id,
                "task_group": a.task_group,
                "desired_status": a.desired_status,
                "client_status": a.client_status,
                "modify_index": a.modify_index,
            }
            for a in self.server.store.allocs()
            if visible(a.namespace)
        ]

    def handle_alloc(self, method, body, query, alloc_id):
        a = self.server.store.alloc_by_id(alloc_id)
        if a is None:
            matches = [
                x for x in self.server.store.allocs() if x.id.startswith(alloc_id)
            ]
            if len(matches) != 1:
                raise APIError(404, f"alloc {alloc_id} not found")
            a = matches[0]
        self._enforce_obj_ns(query, a.namespace, "read-job")
        return encode(a)

    def handle_evals(self, method, body, query):
        self._enforce_ns(query, "read-job")
        visible = self._ns_filter(query, "read-job")
        self._maybe_block(query)
        return [
            encode(e) for e in self.server.store.evals() if visible(e.namespace)
        ]

    def handle_eval(self, method, body, query, eval_id):
        e = self.server.store.eval_by_id(eval_id)
        if e is None:
            raise APIError(404, f"eval {eval_id} not found")
        self._enforce_obj_ns(query, e.namespace, "read-job")
        return encode(e)

    def handle_eval_placement(self, method, body, query, eval_id):
        """GET /v1/evaluations/:id/placement — per-task-group top-k
        score breakdowns + feasibility-rejection histograms for one
        eval (obs/explain.py). Served from the flight recorder's
        explanation ring; evals that aged out of the ring fall back to
        the structured failure metrics the eval itself carries."""
        if method != "GET":
            raise APIError(405, "method not allowed")
        e = self.server.store.eval_by_id(eval_id)
        if e is None:
            # prefix match convenience, same as handle_alloc (CLI ids)
            matches = [
                x
                for x in self.server.store.evals()
                if x.id.startswith(eval_id)
            ]
            if len(matches) != 1:
                raise APIError(404, f"eval {eval_id} not found")
            e = matches[0]
        self._enforce_obj_ns(query, e.namespace, "read-job")
        from ..obs.recorder import flight_recorder

        payload = flight_recorder.explanation(e.id)
        if payload is not None:
            return dict(payload, source="ring")
        if e.failed_tg_allocs:
            groups = {}
            for tg, m in e.failed_tg_allocs.items():
                if isinstance(m, dict):
                    rejections = dict(m.get("rejections", {}) or {})
                    metas = m.get("score_meta", []) or []
                else:
                    rejections = dict(getattr(m, "rejections", {}) or {})
                    metas = getattr(m, "score_meta", []) or []
                groups[tg] = {
                    "failed": True,
                    "rejections": rejections,
                    "top_candidates": [
                        {
                            "node_id": sm["node_id"]
                            if isinstance(sm, dict)
                            else sm.node_id,
                            "rank": i + 1,
                            "final_score": sm["norm_score"]
                            if isinstance(sm, dict)
                            else sm.norm_score,
                            "components": dict(
                                sm["scores"]
                                if isinstance(sm, dict)
                                else sm.scores
                            ),
                            "placed": 0,
                        }
                        for i, sm in enumerate(metas)
                    ],
                }
            return {
                "eval_id": e.id,
                "job_id": e.job_id,
                "namespace": e.namespace,
                "groups": groups,
                "source": "failed_tg_allocs",
            }
        raise APIError(
            404,
            f"no placement explanation for eval {e.id} "
            "(aged out of the ring, or placement_explanations disabled)",
        )

    def handle_alloc_explain(self, method, body, query, alloc_id):
        """GET /v1/allocations/:id/explain — why this alloc landed on
        its node: the alloc's own per-component score row plus (when
        the eval is still in the explanation ring) the group-level
        candidate table and rejection histogram."""
        if method != "GET":
            raise APIError(405, "method not allowed")
        a = self.server.store.alloc_by_id(alloc_id)
        if a is None:
            matches = [
                x
                for x in self.server.store.allocs()
                if x.id.startswith(alloc_id)
            ]
            if len(matches) != 1:
                raise APIError(404, f"alloc {alloc_id} not found")
            a = matches[0]
        self._enforce_obj_ns(query, a.namespace, "read-job")
        from ..obs.recorder import flight_recorder

        metrics = a.metrics
        out = {
            "alloc_id": a.id,
            "name": a.name,
            "job_id": a.job_id,
            "task_group": a.task_group,
            "node_id": a.node_id,
            "eval_id": a.eval_id,
            "scores": dict(getattr(metrics, "scores", {}) or {}),
            "score_meta": encode(getattr(metrics, "score_meta", []) or []),
        }
        payload = (
            flight_recorder.explanation(a.eval_id) if a.eval_id else None
        )
        if payload is not None:
            group = (payload.get("groups") or {}).get(a.task_group)
            if group is not None:
                out["explanation"] = group
        return out

    def handle_alloc_stop(self, method, body, query, alloc_id):
        """POST /v1/allocation/:id/stop (alloc_endpoint.go Stop): mark
        the alloc for migration and evaluate its job."""
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        a = self.server.store.alloc_by_id(alloc_id)
        if a is None:
            # prefix match convenience, same as handle_alloc (CLI ids)
            matches = [
                x
                for x in self.server.store.allocs()
                if x.id.startswith(alloc_id)
            ]
            if len(matches) != 1:
                raise APIError(404, f"alloc {alloc_id} not found")
            a = matches[0]
        self._enforce_obj_ns(query, a.namespace, "submit-job")
        ev = self.server.stop_alloc(a.id)
        if ev is None:
            raise APIError(400, "alloc is already terminal")
        return {"eval_id": ev.id}

    def handle_scheduler_config(self, method, body, query):
        cfg = self.server.store.scheduler_config()
        if method == "GET":
            self._enforce(query, "operator_read")
            from ..scheduler import algorithms as sched_algorithms

            return {
                "scheduler_algorithm": cfg.scheduler_algorithm,
                "available_algorithms": sched_algorithms.available(),
                "preemption_config": {
                    "system_scheduler_enabled": cfg.preemption_system_enabled,
                    "batch_scheduler_enabled": cfg.preemption_batch_enabled,
                    "service_scheduler_enabled": cfg.preemption_service_enabled,
                },
                "memory_oversubscription_enabled": cfg.memory_oversubscription_enabled,
                "pause_eval_broker": cfg.pause_eval_broker,
                "placement_explanations": getattr(
                    cfg, "placement_explanations", True
                ),
                "throughput_source": getattr(
                    cfg, "throughput_source", "declared"
                ),
            }
        if method in ("POST", "PUT"):
            self._enforce(query, "operator_write")
            if not body:
                raise APIError(400, "missing body")
            from ..state import SchedulerConfiguration

            pc = body.get("preemption_config", {})
            new_cfg = SchedulerConfiguration(
                scheduler_algorithm=body.get(
                    "scheduler_algorithm", cfg.scheduler_algorithm
                ),
                preemption_system_enabled=pc.get(
                    "system_scheduler_enabled", cfg.preemption_system_enabled
                ),
                preemption_batch_enabled=pc.get(
                    "batch_scheduler_enabled", cfg.preemption_batch_enabled
                ),
                preemption_service_enabled=pc.get(
                    "service_scheduler_enabled", cfg.preemption_service_enabled
                ),
                placement_explanations=body.get(
                    "placement_explanations",
                    getattr(cfg, "placement_explanations", True),
                ),
                throughput_source=body.get(
                    "throughput_source",
                    getattr(cfg, "throughput_source", "declared"),
                ),
            )
            from ..scheduler import algorithms as sched_algorithms

            if not sched_algorithms.is_registered(new_cfg.scheduler_algorithm):
                raise APIError(
                    400,
                    "scheduler_algorithm must be one of: "
                    + "|".join(sched_algorithms.available()),
                )
            from ..scheduler.hetero import THROUGHPUT_SOURCES

            if new_cfg.throughput_source not in THROUGHPUT_SOURCES:
                raise APIError(
                    400,
                    "throughput_source must be one of: "
                    + "|".join(THROUGHPUT_SOURCES),
                )
            self.server.raft_apply(MsgType.SCHED_CONFIG, {"config": new_cfg})
            return {"updated": True}
        raise APIError(405, f"method {method} not allowed")

    def handle_hetero_placements(self, method, body, query):
        """GET /v1/operator/scheduler/placements — live allocation counts
        per device class, overall and per job: the observable effect of
        choosing a hetero-* algorithm (scheduler/hetero.py). Also carries
        the topology occupancy view (allocs/nodes per rack and per pod,
        from node.topology) and per-gang intactness — the observable
        effect of cp-gang and the law-15 atomic-commit seam."""
        if method != "GET":
            raise APIError(405, "method not allowed")
        self._enforce(query, "operator_read")
        store = self.server.store
        cfg = store.scheduler_config()
        per_class: dict[str, int] = {}
        per_job: dict[str, dict[str, int]] = {}
        nodes_per_class: dict[str, int] = {}
        per_rack: dict[str, dict[str, int]] = {}
        per_pod: dict[str, dict[str, int]] = {}
        for node in store.nodes():
            dc = node.device_class
            nodes_per_class[dc] = nodes_per_class.get(dc, 0) + 1
            topo = getattr(node, "topology", None) or {}
            rack = per_rack.setdefault(
                topo.get("rack", ""), {"nodes": 0, "allocs": 0}
            )
            pod = per_pod.setdefault(
                topo.get("pod", ""), {"nodes": 0, "allocs": 0}
            )
            rack["nodes"] += 1
            pod["nodes"] += 1
            for a in store.allocs_by_node(node.id):
                if a.terminal_status():
                    continue
                per_class[dc] = per_class.get(dc, 0) + 1
                rack["allocs"] += 1
                pod["allocs"] += 1
                jk = f"{a.namespace}/{a.job_id}"
                jc = per_job.setdefault(jk, {})
                jc[dc] = jc.get(dc, 0) + 1
        gangs: dict[str, dict] = {}
        for job in store.jobs():
            gang = getattr(job, "gang", None) or {}
            members = list(gang.get("groups") or ())
            if not members or job.stopped():
                continue
            desired = job.required_allocs()
            live = {m: 0 for m in members}
            for a in store.allocs_by_job(job.namespace, job.id):
                if not a.terminal_status() and a.task_group in live:
                    live[a.task_group] += 1
            gangs[f"{job.namespace}/{job.id}"] = {
                "members": dict(sorted(live.items())),
                "desired": {
                    m: desired.get(m, 0) for m in sorted(members)
                },
                "intact": all(
                    live[m] == desired.get(m, 0) for m in members
                ),
            }
        return {
            "scheduler_algorithm": cfg.scheduler_algorithm,
            "nodes_per_class": dict(sorted(nodes_per_class.items())),
            "allocs_per_class": dict(sorted(per_class.items())),
            "jobs": {
                k: dict(sorted(v.items()))
                for k, v in sorted(per_job.items())
            },
            "topology": {
                "racks": dict(sorted(per_rack.items())),
                "pods": dict(sorted(per_pod.items())),
            },
            "gangs": dict(sorted(gangs.items())),
        }

    def handle_job_versions(self, method, body, query, job_id):
        """GET /v1/job/:id/versions (job_endpoint.go GetJobVersions)."""
        ns = query.get("namespace", "default")
        self._enforce_obj_ns(query, ns, "read-job")
        versions = self.server.store.job_versions_list(ns, job_id)
        if not versions:
            cur = self.server.store.job_by_id(ns, job_id)
            if cur is None:
                raise APIError(404, f"job {job_id} not found")
            versions = [cur]
        return {
            "versions": [encode(j) for j in sorted(
                versions, key=lambda j: -j.version
            )],
        }

    def handle_job_revert(self, method, body, query, job_id):
        """POST /v1/job/:id/revert {"job_version": N} — re-registers the
        prior version (the rollback is itself a new version, like the
        reference's Job.Revert)."""
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        ns = query.get("namespace", "default")
        self._enforce_obj_ns(query, ns, "submit-job")
        if not body or "job_version" not in body:
            raise APIError(400, "missing 'job_version'")
        import copy as _copy

        old = self.server.store.job_version(
            ns, job_id, int(body["job_version"])
        )
        if old is None:
            raise APIError(
                404, f"job {job_id} version {body['job_version']} not found"
            )
        ev = self.server.register_job(_copy.deepcopy(old))
        return {"eval_id": getattr(ev, "id", ""), "reverted_to": old.version}

    def handle_job_evaluate(self, method, body, query, job_id):
        """POST /v1/job/:id/evaluate — force a new evaluation
        (job_endpoint.go Evaluate)."""
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        ns = query.get("namespace", "default")
        self._enforce_obj_ns(query, ns, "submit-job")
        job = self.server.store.job_by_id(ns, job_id)
        if job is None:
            raise APIError(404, f"job {job_id} not found")
        if job.is_periodic() or job.is_parameterized():
            # templates never get direct evals (job_endpoint.go Evaluate
            # rejects them; they run via periodic launch / dispatch)
            raise APIError(
                400, "can't evaluate periodic/parameterized job"
            )
        from ..structs import Evaluation
        from ..structs.evaluation import EVAL_STATUS_PENDING

        # admission gate BEFORE the eval is committed: apply_eval_create
        # is shared with internal worker followups and must stay
        # ungated, so the external trigger checks in explicitly here
        self.server.admission.check_intake(job.priority, "job-eval")
        ev = Evaluation(
            namespace=ns,
            priority=job.priority,
            type=job.type,
            triggered_by="job-eval",
            job_id=job_id,
            status=EVAL_STATUS_PENDING,
        )
        self.server.apply_eval_create([ev])
        return {"eval_id": ev.id}

    def handle_system_gc(self, method, body, query):
        """PUT /v1/system/gc — force one GC sweep
        (system_endpoint.go GarbageCollect → the _core job path)."""
        if method not in ("POST", "PUT"):
            raise APIError(405, "PUT required")
        self._enforce(query, "operator_write")
        # the manual sweep waives the age thresholds (the reference's
        # forced _core GC ignores them too)
        reaped = self.server.core_gc.gc_all(force=True)
        return {"reaped": reaped}

    def handle_raft_configuration(self, method, body, query):
        """GET /v1/operator/raft/configuration — the voting set
        (operator_endpoint.go RaftGetConfiguration)."""
        if method != "GET":
            raise APIError(405, f"method {method} not allowed")
        self._enforce(query, "operator_read")
        raft = self.server.raft
        leader = raft.leader_id()
        servers = [
            {
                "id": pid,
                "address": addr,
                "leader": pid == leader,
                "voter": True,
            }
            for pid, addr in sorted(raft.peers().items())
        ]
        return {"servers": servers, "index": self.server.store.latest_index}

    def handle_raft_peer(self, method, body, query):
        """DELETE /v1/operator/raft/peer?id=<node_id> — remove a peer from
        the voting set (operator_endpoint.go RaftRemovePeerByID)."""
        if method != "DELETE":
            raise APIError(405, f"method {method} not allowed")
        self._enforce(query, "operator_write")
        pid = (query.get("id") or [""])[0]
        if not pid:
            raise APIError(400, "missing ?id=<node_id>")
        from ..raft import NotLeaderError

        try:
            self.server.raft.remove_peer(pid)
        except ValueError as e:
            raise APIError(400, str(e))
        except NotLeaderError as e:
            # membership changes commit on the leader; tell the operator
            # where to retry instead of a bare 500 (the CLI surfaces it)
            raise APIError(
                421,
                f"not the leader — retry against "
                f"{e.leader_addr or e.leader_id or 'the leader'}",
            )
        return {"removed": pid}

    def handle_operator_defrag(self, method, body, query):
        """/v1/operator/defrag — the live-migration control plane.

        GET returns the controller's status block (enabled/paused,
        interval, budget, packing-efficiency gauge, move counters).
        POST triggers an immediate defrag cycle regardless of the
        periodic interval; ``{"paused": true|false}`` in the body flips
        the pause latch instead (a paused controller plans nothing but
        keeps serving recovery via trigger)."""
        defrag = self.server.defrag
        if method == "GET":
            self._enforce(query, "operator_read")
            return defrag.status()
        if method not in ("POST", "PUT"):
            raise APIError(405, f"method {method} not allowed")
        self._enforce(query, "operator_write")
        if isinstance(body, dict) and "paused" in body:
            defrag.paused = bool(body["paused"])
            return defrag.status()
        defrag.trigger()
        out = defrag.status()
        out["triggered"] = True
        return out

    def handle_job_dispatch(self, method, body, query, job_id):
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        self._enforce_ns(query, "dispatch-job")
        body = body or {}
        ns = query.get("namespace", "default")
        import base64

        payload = base64.b64decode(body.get("payload", "") or "")
        try:
            child, ev = self.server.dispatch_job(
                ns, job_id, payload=payload, meta=body.get("meta") or {}
            )
        except ValueError as e:
            raise APIError(400, str(e)) from None
        return {"dispatched_job_id": child.id, "eval_id": ev.id}

    def handle_periodic_force(self, method, body, query, job_id):
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        self._enforce_ns(query, "submit-job")
        job = self._get_job(job_id, query)
        if not job.is_periodic():
            raise APIError(400, f"job {job_id} is not periodic")
        child = self.server.periodic.force_launch(job)
        if child is None:
            raise APIError(400, "launch skipped (prohibit_overlap)")
        return {"launched_job_id": child.id}

    # -- namespaces (namespace_endpoint.go) --------------------------------
    def handle_namespaces(self, method, body, query):
        if method != "GET":
            raise APIError(405, "method not allowed")
        acl = self._acl(query)
        out = [
            {
                "name": n.name, "description": n.description,
                "create_index": n.create_index,
                "modify_index": n.modify_index,
            }
            for n in self.server.store.namespaces()
        ]
        # the default namespace always exists implicitly
        if not any(n["name"] == "default" for n in out):
            out.insert(0, {"name": "default",
                           "description": "Default shared namespace",
                           "create_index": 1, "modify_index": 1})
        if acl is not None:  # List filters to visible namespaces
            out = [n for n in out if acl.allow_namespace(n["name"])]
        return sorted(out, key=lambda n: n["name"])

    def handle_namespace(self, method, body, query, name):
        from ..structs.job import Namespace

        if method == "GET":
            acl = self._acl(query)
            if acl is not None and not acl.allow_namespace(name):
                raise APIError(403, "Permission denied")
            if name == "default":
                return {"name": "default",
                        "description": "Default shared namespace"}
            ns = self.server.store.namespace_by_name(name)
            if ns is None:
                raise APIError(404, f"namespace not found: {name}")
            return encode(ns)
        if method in ("PUT", "POST"):
            self._enforce_management(query)
            ns = Namespace(
                name=name,
                description=(body or {}).get("description", ""),
            )
            try:
                self.server.upsert_namespace(ns)
            except ValueError as e:
                raise APIError(400, str(e)) from None
            return {"index": self.server.store.latest_index}
        if method == "DELETE":
            self._enforce_management(query)
            try:
                self.server.delete_namespace(name)
            except KeyError as e:
                raise APIError(404, str(e)) from None
            except ValueError as e:
                raise APIError(409, str(e)) from None
            return {"index": self.server.store.latest_index}
        raise APIError(405, "method not allowed")

    def handle_namespace_create(self, method, body, query):
        if method not in ("PUT", "POST"):
            raise APIError(405, "PUT required")
        name = (body or {}).get("name", "")
        return self.handle_namespace("PUT", body, query, name)

    # -- scaling (job_endpoint Scale + scaling_endpoint.go) -----------------
    def handle_job_scale(self, method, body, query, job_id):
        ns = query.get("namespace", "default")
        if method == "GET":
            self._enforce_ns(query, "read-job-scaling")
            job = self.server.store.job_by_id(ns, job_id)
            if job is None:
                raise APIError(404, f"job not found: {job_id}")
            return {
                "job_id": job.id,
                "namespace": job.namespace,
                "job_stopped": job.stopped(),
                "task_groups": {
                    tg.name: {
                        "desired": tg.count,
                        "running": sum(
                            1
                            for a in self.server.store.allocs_by_job(ns, job.id)
                            if a.task_group == tg.name
                            and a.client_status == "running"
                        ),
                        "events": self.server.store.scaling_events(ns, job.id),
                    }
                    for tg in job.task_groups
                },
            }
        if method in ("POST", "PUT"):
            self._enforce_ns(query, "scale-job")
            body = body or {}
            target = body.get("target", {})
            group = target.get("group") or target.get("Group")
            count = body.get("count")
            if not group or count is None:
                raise APIError(400, "target.group and count required")
            try:
                ev = self.server.scale_job(
                    ns, job_id, group, int(count),
                    message=body.get("message", ""),
                    error=bool(body.get("error", False)),
                )
            except KeyError as e:
                raise APIError(404, str(e)) from None
            except ValueError as e:
                raise APIError(400, str(e)) from None
            return {"eval_id": ev.id, "index": self.server.store.latest_index}
        raise APIError(405, "method not allowed")

    def handle_scaling_policies(self, method, body, query):
        if method != "GET":
            raise APIError(405, "method not allowed")
        self._enforce_ns(query, "list-scaling-policies")
        visible = self._ns_filter(query, "list-scaling-policies")
        out = []
        for job in self.server.store.jobs():
            if not visible(job.namespace):
                continue
            for tg in job.task_groups:
                if tg.scaling is not None:
                    out.append(
                        {
                            "id": f"{job.namespace}/{job.id}/{tg.name}",
                            "namespace": job.namespace,
                            "job_id": job.id,
                            "group": tg.name,
                            "min": tg.scaling.min,
                            "max": tg.scaling.max,
                            "enabled": tg.scaling.enabled,
                            "policy": tg.scaling.policy,
                        }
                    )
        return out

    # -- search (nomad/search_endpoint.go) ----------------------------------
    SEARCH_CONTEXTS = ("jobs", "nodes", "allocs", "evals", "deployments",
                       "volumes", "namespaces")
    SEARCH_TRUNCATE = 20  # search_endpoint.go truncateLimit

    def handle_search(self, method, body, query):
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        body = body or {}
        prefix = body.get("prefix", "")
        context = body.get("context", "all") or "all"
        contexts = (
            self.SEARCH_CONTEXTS if context == "all" else (context,)
        )
        ns = query.get("namespace", "default")
        store = self.server.store
        matches, truncations = {}, {}

        def collect(name, ids):
            hits = sorted(i for i in ids if i.startswith(prefix))
            truncations[name] = len(hits) > self.SEARCH_TRUNCATE
            matches[name] = hits[: self.SEARCH_TRUNCATE]

        for ctx in contexts:
            if ctx == "jobs":
                self._enforce_ns(query, "read-job")
                collect("jobs", [
                    j.id for j in store.jobs() if j.namespace == ns
                ])
            elif ctx == "nodes":
                collect("nodes", [n.id for n in store.nodes()])
            elif ctx == "allocs":
                collect("allocs", [
                    a.id for a in store.allocs() if a.namespace == ns
                ])
            elif ctx == "evals":
                collect("evals", [
                    e.id for e in store.evals() if e.namespace == ns
                ])
            elif ctx == "deployments":
                collect("deployments", [
                    d.id for d in store.deployments() if d.namespace == ns
                ])
            elif ctx == "volumes":
                collect("volumes", [v.id for v in store.csi_volumes()])
            elif ctx == "namespaces":
                names = [n.name for n in store.namespaces()] + ["default"]
                collect("namespaces", names)
            else:
                raise APIError(400, f"invalid context {ctx!r}")
        return {"matches": matches, "truncations": truncations}

    # -- client fs/logs proxy (command/agent/fs_endpoint.go) ---------------
    def _client_rpc_for_alloc(self, alloc_id, query):
        """Resolve alloc → node → the client's advertised RPC address
        (client/fs_endpoint.go reachability via node attribute)."""
        from ..client.endpoints import ATTR_RPC_ADDR
        from ..rpc import RPCClient

        alloc = self.server.store.alloc_by_id(alloc_id)
        if alloc is None:
            matches = [
                x for x in self.server.store.allocs()
                if x.id.startswith(alloc_id)
            ]
            if len(matches) != 1:
                raise APIError(404, f"alloc not found: {alloc_id}")
            alloc = matches[0]
        self._enforce_obj_ns(query, alloc.namespace, "read-fs")
        node = self.server.store.node_by_id(alloc.node_id)
        addr = (node.attributes or {}).get(ATTR_RPC_ADDR) if node else None
        if not addr:
            raise APIError(
                404, f"node for alloc {alloc.id[:8]} has no client RPC"
            )
        return RPCClient(addr), alloc

    def handle_fs_ls(self, method, body, query, alloc_id):
        if method != "GET":
            raise APIError(405, "method not allowed")
        c, alloc = self._client_rpc_for_alloc(alloc_id, query)
        try:
            return c.call(
                "FS.list",
                {"alloc_id": alloc.id, "path": query.get("path", "/")},
            )
        finally:
            c.close()

    def handle_fs_cat(self, method, body, query, alloc_id):
        if method != "GET":
            raise APIError(405, "method not allowed")
        c, alloc = self._client_rpc_for_alloc(alloc_id, query)
        try:
            data = c.call(
                "FS.read",
                {
                    "alloc_id": alloc.id,
                    "path": query.get("path", "/"),
                    "offset": int(query.get("offset", 0)),
                    "limit": int(query.get("limit", 1 << 20)),
                },
            )
            return {"data": data.decode("utf-8", "replace")}
        finally:
            c.close()

    def handle_fs_logs(self, method, body, query, alloc_id):
        if method != "GET":
            raise APIError(405, "method not allowed")
        task = query.get("task")
        if not task:
            raise APIError(400, "task parameter required")
        c, alloc = self._client_rpc_for_alloc(alloc_id, query)
        follow = query.get("follow", "") in ("true", "1")

        def gen():
            try:
                for chunk in c.stream(
                    "FS.logs",
                    {
                        "alloc_id": alloc.id,
                        "task": task,
                        "type": query.get("type", "stdout"),
                        "follow": follow,
                        "offset": int(query.get("offset", 0)),
                    },
                    timeout=3600 if follow else 30,
                ):
                    yield json.dumps(chunk)  # NDJSON frames
            finally:
                c.close()

        return StreamingResponse(gen())

    def handle_event_stream(self, method, body, query):
        """NDJSON event stream (http.go:359 /v1/event/stream). Events are
        ACL-filtered per topic: Node events need node:read, namespaced
        topics need read-job on the event's namespace (the reference's
        aclFilter in nomad/stream/event_broker.go). The token is
        re-resolved on every poll so revocation/downgrade takes effect on
        long-lived streams (event_broker.go checkSubscriptionACLs)."""
        self._acl(query)  # reject bad tokens before subscribing
        secret = query.get("_secret", "")

        def current_acl():
            from ..server.acl import TokenError

            try:
                return self.server.acl.resolve_token(secret)
            except TokenError:
                return False  # token revoked mid-stream: terminate

        def event_visible(ev, acl) -> bool:
            if acl is None or acl.is_management():
                return True
            if ev.topic == "Node":
                return acl.allow_node_read()
            return acl.allow_namespace_operation(
                ev.namespace or "default", "read-job"
            )

        from_index = int(query.get("index", 0) or 0)
        topics = None
        if "topic" in query:
            # topic=Job:* or topic=Node:node-id
            topics = {}
            for spec in query["topic"].split(","):
                topic, _, key = spec.partition(":")
                topics.setdefault(topic, []).append(key or "*")
        limit = int(query.get("limit", 0) or 0)  # test hook: stop after N
        sub = self.server.events.subscribe(topics, from_index)

        def gen():
            n = 0
            deadline = None
            wait = float(query.get("wait", 30.0) or 30.0)
            import time as _t

            deadline = _t.time() + wait
            while _t.time() < deadline:
                acl = current_acl()
                if acl is False:
                    return  # token revoked: close the stream
                for ev in sub.next_events(timeout=0.5):
                    if not event_visible(ev, acl):
                        continue
                    yield ev.to_json()
                    n += 1
                    if limit and n >= limit:
                        return

        return StreamingResponse(gen())

    def handle_snapshot_save(self, method, body, query):
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        self._enforce(query, "operator_write")
        path = (body or {}).get("path")
        if not path:
            raise APIError(400, "missing 'path'")
        from ..state.snapshot import save_snapshot

        index = save_snapshot(self.server.store, path)
        return {"index": index, "path": path}

    def handle_agent_self(self, method, body, query):
        self._enforce(query, "agent_read")
        out = {
            "member": {"name": "server-1", "status": "alive"},
            "stats": {
                "worker_count": len(self.server.workers),
                "plan_queue_depth": self.server.plan_queue.depth(),
                "blocked_evals": self.server.blocked_evals.blocked_count(),
            },
            "version": __import__("nomad_tpu").__version__,
        }
        if self.client is not None:
            out["client"] = {
                "node_id": self.client.node.id,
                "allocs_running": self.client.num_allocs(),
            }
        return out

    def handle_leader(self, method, body, query):
        return f"{self.host}:{self.port}"

    def handle_metrics(self, method, body, query):
        self._enforce(query, "agent_read")
        from ..utils.metrics import global_metrics

        return global_metrics.snapshot()

    def handle_pprof(self, method, body, query, kind):
        """/v1/agent/pprof/{goroutine,profile,heap} — thread dump,
        sampling CPU profile, heap stats (utils/profile.py; reference
        command/agent/http.go:331 gates these behind agent:read too)."""
        self._enforce(query, "agent_read")
        from ..utils import profile as prof

        if kind == "goroutine":
            return prof.thread_dump()
        if kind == "profile":
            seconds = min(float(query.get("seconds", 1.0)), 30.0)
            return prof.sample_profile(seconds)
        if kind == "heap":
            return prof.heap_profile()
        raise APIError(404, f"unknown pprof kind {kind!r}")

    def handle_operator_debug(self, method, body, query):
        self._enforce(query, "agent_read")
        from ..utils.profile import debug_bundle

        return debug_bundle(self.server)

    def handle_agent_trace(self, method, body, query, eval_id=None):
        """/v1/agent/trace[/{eval_id}] — flight-recorder dump: recent
        completed eval traces (summaries), last-N error events, and the
        per-kernel jit profile; with an eval id, the full span tree."""
        self._enforce(query, "agent_read")
        from ..obs.recorder import flight_recorder

        if eval_id:
            trace = flight_recorder.get(eval_id)
            if trace is None:
                raise APIError(404, f"no trace for eval {eval_id!r}")
            return trace
        from ..utils.backend import kernel_profile

        # canonical jaxpr fingerprints for every kernel this process has
        # traced (jaxlint JXL006): lets an operator diff two agents'
        # compiled programs from their trace surfaces alone. Re-tracing
        # is abstract (no compile) and cached per (kernel, spec); the
        # flight-recorder surface must never 500 because a kernel spec
        # went unretraceable, hence best-effort.
        try:
            from ..analysis.jaxlint import fingerprint_table

            fingerprints = fingerprint_table()
        except Exception:  # noqa: BLE001
            fingerprints = {}
        return {
            "traces": flight_recorder.list(int(query.get("n", 50))),
            "errors": flight_recorder.errors(),
            "kernels": kernel_profile(),
            "kernel_fingerprints": fingerprints,
            # incremental-rescoring accounting (device/cache.py):
            # rows patched vs served resident, generation swaps, and
            # the pipeline-overlap wall time the commit thread hid
            "device_cache": self.server.device_cache.device_counters(),
            # gang scheduling ledger: kernel-level commits/releases
            # (scheduler/cp.py nomad.cp.gang_*) plus the law-15 atomic
            # release seam (scheduler/generic.py nomad.gang.*)
            "gang": self._gang_counters(),
            # migration-plane ledger (server/defrag.py, law 16): the
            # two-phase move counters plus the drainer's graceful-vs-
            # forced exit split
            "migrate": self._migrate_counters(),
        }

    @staticmethod
    def _gang_counters() -> dict:
        from ..utils.metrics import global_metrics

        counters = global_metrics.snapshot()["counters"]
        return {
            k: v
            for k, v in sorted(counters.items())
            if k.startswith(("nomad.gang.", "nomad.cp.gang_"))
        }

    @staticmethod
    def _migrate_counters() -> dict:
        from ..utils.metrics import global_metrics

        snap = global_metrics.snapshot()
        out = {
            k: v
            for k, v in sorted(snap["counters"].items())
            if k.startswith(("nomad.migrate.", "nomad.drain."))
        }
        gauge = snap["gauges"].get("nomad.migrate.packing_efficiency")
        if gauge is not None:
            out["nomad.migrate.packing_efficiency"] = round(gauge, 6)
        return out

    def handle_agent_resilience(self, method, body, query):
        """/v1/agent/resilience — per-kernel circuit-breaker snapshots,
        the forced-open override, recent trip events from the flight
        recorder, and the resilience counter slice of the metrics
        registry (``nomad-tpu resilience status`` reads this)."""
        self._enforce(query, "agent_read")
        from ..obs.recorder import flight_recorder
        from ..resilience.breaker import forced_open, snapshot_all
        from ..utils.metrics import global_metrics

        counters = global_metrics.snapshot()["counters"]
        srv = self.server
        return {
            "breakers": snapshot_all(),
            "forced_open": forced_open(),
            "recent_trips": [
                e
                for e in flight_recorder.errors()
                if e.get("component") == "resilience"
            ],
            "lanes": {
                "lane_mode": srv.lane_mode,
                "num_lanes": srv.lanes.num_lanes,
                "num_batch_workers": srv.lanes.num_batch_workers,
                "assignments": {
                    str(w): list(ls)
                    for w, ls in srv.lanes.assignments().items()
                },
                "claims": srv.lane_claims.snapshot(),
            },
            "admission": (
                srv.admission.snapshot()
                if getattr(srv, "admission", None) is not None
                else None
            ),
            "counters": {
                k: v
                for k, v in counters.items()
                if k.startswith("nomad.resilience.")
                or k.startswith("nomad.plan.lane_")
                or k.startswith("nomad.worker.lane_")
                or k.startswith("nomad.admission.")
                or k == "nomad.plan.cross_lane_handoffs"
                or k == "nomad.broker.nack_redelivery_delayed"
            },
        }

    def handle_agent_calibration(self, method, body, query):
        """/v1/agent/calibration — the calibration plane: every
        operational constant with its provenance (default/probe/
        learned), the loaded probe artifact if any, the throughput
        estimator's learned cells, and the active throughput source
        (``nomad-tpu calibrate status|report`` reads this)."""
        self._enforce(query, "agent_read")
        srv = self.server
        cfg = srv.store.scheduler_config()
        table = getattr(srv, "calibration", None)
        est = getattr(srv, "throughput_estimator", None)
        if table is None:
            from ..obs.calibrate import global_table as table
        if est is None:
            from ..obs.calibrate import global_estimator as est
        return {
            "table": table.snapshot(),
            "estimator": est.snapshot(),
            "throughput_source": getattr(
                cfg, "throughput_source", "declared"
            ),
        }

    def handle_agent_slo(self, method, body, query):
        """/v1/agent/slo — the live SLO report: eval/placement latency
        percentiles from the always-on ``nomad.slo.*`` series the
        flight recorder feeds, current queue depth, resilience/lane
        counters, flight-recorder ring coverage, and the verdict
        against targets (defaults; override any ``SloTargets`` field
        via a query parameter, e.g. ``?eval_p99_ms=100``)."""
        self._enforce(query, "agent_read")
        from ..obs.slo import SloTargets, live_report

        targets = SloTargets()
        for f in SloTargets.FIELDS:
            if f in query:
                raw = query[f]
                setattr(
                    targets, f,
                    None if raw in ("", "none", "null") else float(raw),
                )
        return live_report(self.server, targets)

    # -- ACL endpoints (nomad/acl_endpoint.go) -----------------------------
    def handle_acl_bootstrap(self, method, body, query):
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        try:
            token = self.server.acl.bootstrap()
        except PermissionError as e:
            raise APIError(400, str(e)) from None
        return token.to_api()

    def handle_acl_policies(self, method, body, query):
        self._enforce_management(query)
        self._maybe_block(query)
        return [
            {
                "Name": p.name,
                "Description": p.description,
                "CreateIndex": p.create_index,
                "ModifyIndex": p.modify_index,
            }
            for p in self.server.store.acl_policies()
        ]

    def handle_acl_policy(self, method, body, query, name):
        from ..acl import ACLPolicyRecord, AclPolicyError

        if method == "GET":
            # a token may read the policies attached to itself
            acl = self._acl(query)
            if acl is not None and not acl.is_management():
                token = self.server.store.acl_token_by_secret(
                    query.get("_secret", "")
                )
                if token is None or name not in token.policies:
                    raise APIError(403, "Permission denied")
            p = self.server.store.acl_policy_by_name(name)
            if p is None:
                raise APIError(404, f"policy {name} not found")
            return p.to_api()
        if method in ("POST", "PUT"):
            self._enforce_management(query)
            body = body or {}
            rec = ACLPolicyRecord(
                name=name,
                description=body.get("Description", body.get("description", "")),
                rules=body.get("Rules", body.get("rules", "")),
            )
            try:
                self.server.acl.upsert_policies([rec])
            except (AclPolicyError, ValueError) as e:
                raise APIError(400, str(e)) from None
            return {"updated": True}
        if method == "DELETE":
            self._enforce_management(query)
            self.server.acl.delete_policies([name])
            return {"deleted": True}
        raise APIError(405, f"method {method} not allowed")

    def handle_acl_tokens(self, method, body, query):
        self._enforce_management(query)
        self._maybe_block(query)
        return [t.to_api(redact_secret=True) for t in self.server.store.acl_tokens()]

    def handle_acl_token_create(self, method, body, query):
        if method not in ("POST", "PUT"):
            raise APIError(405, "POST required")
        self._enforce_management(query)
        from ..acl import ACLToken

        body = body or {}
        token = ACLToken(
            name=body.get("Name", body.get("name", "")),
            type=body.get("Type", body.get("type", "client")),
            policies=body.get("Policies", body.get("policies", [])) or [],
            global_=body.get("Global", body.get("global", False)),
        )
        try:
            self.server.acl.upsert_tokens([token])
        except ValueError as e:
            raise APIError(400, str(e)) from None
        return token.to_api()

    def handle_acl_token_self(self, method, body, query):
        if method != "GET":
            raise APIError(405, "GET required")
        secret = query.get("_secret", "")
        token = self.server.store.acl_token_by_secret(secret)
        if token is None:
            raise APIError(403, "ACL token not found")
        return token.to_api()

    def handle_acl_token(self, method, body, query, accessor):
        if method == "GET":
            self._enforce_management(query)
            t = self.server.store.acl_token_by_accessor(accessor)
            if t is None:
                raise APIError(404, f"token {accessor} not found")
            return t.to_api()
        if method == "DELETE":
            self._enforce_management(query)
            self.server.acl.delete_tokens([accessor])
            return {"deleted": True}
        raise APIError(405, f"method {method} not allowed")
