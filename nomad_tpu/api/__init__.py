"""L6/L7 API surface: HTTP agent, JSON codec, Python SDK."""

from .client import APIException, NomadClient
from .codec import decode_job, decode_node, encode
from .http import HTTPAgent

__all__ = ["HTTPAgent", "NomadClient", "APIException", "encode", "decode_job", "decode_node"]
