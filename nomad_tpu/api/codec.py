"""JSON codec for the shared structs.

The reference's api/ package mirrors every struct with JSON tags; here one
generic dataclass encoder/decoder covers the API surface. Heavy pointers
(alloc.job) are stubbed out, mirroring Allocation.Stub()."""

from __future__ import annotations

import dataclasses
from typing import Any

from ..structs import (
    Affinity,
    Allocation,
    AllocMetric,
    Constraint,
    Evaluation,
    Job,
    Node,
    Resources,
    Spread,
    SpreadTarget,
    Task,
    TaskGroup,
)
from ..structs.alloc import NodeScoreMeta
from ..structs.job import (
    EphemeralDisk,
    MigrateStrategy,
    ParameterizedJobConfig,
    PeriodicConfig,
    ReschedulePolicy,
    RestartPolicy,
    UpdateStrategy,
)
from ..structs.resources import (
    AllocatedDeviceResource,
    NetworkResource,
    NodeDeviceInstance,
    NodeDeviceResource,
    NodeReservedResources,
    NodeResources,
    RequestedDevice,
)
from ..structs.volumes import (
    CSINodeInfo,
    ClientHostVolumeConfig,
    VolumeMount,
    VolumeRequest,
)


def encode(obj: Any, *, _depth: int = 0) -> Any:
    """Dataclass → JSON-able dict (recursively), dropping private and
    heavyweight fields."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            if f.name in ("job",):  # stub heavy pointers
                continue
            out[f.name] = encode(getattr(obj, f.name), _depth=_depth + 1)
        return out
    if isinstance(obj, dict):
        return {str(k): encode(v, _depth=_depth + 1) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [encode(v, _depth=_depth + 1) for v in obj]
    if isinstance(obj, bytes):
        return obj.decode("utf-8", "replace")
    if hasattr(obj, "__dict__") and not isinstance(
        obj, (str, int, float, bool, type(None))
    ):
        return {
            k: encode(v, _depth=_depth + 1)
            for k, v in vars(obj).items()
            if not k.startswith("_")
        }
    return obj


def _decode_into(cls, data: dict):
    """dict → dataclass, ignoring unknown keys (forward compatibility)."""
    if data is None:
        return None
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        val = data[f.name]
        kwargs[f.name] = _decode_field(f.type, f.name, val)
    return cls(**kwargs)


_NESTED = {
    "resources": Resources,
    "restart_policy": RestartPolicy,
    "reschedule_policy": ReschedulePolicy,
    "ephemeral_disk": EphemeralDisk,
    "update": UpdateStrategy,
    "migrate": MigrateStrategy,
    "periodic": PeriodicConfig,
    "parameterized": ParameterizedJobConfig,
    "node_resources": NodeResources,
    "reserved": NodeReservedResources,
}
_NESTED_LISTS = {
    "constraints": Constraint,
    "affinities": Affinity,
    "spreads": Spread,
    "targets": SpreadTarget,
    "tasks": Task,
    "task_groups": TaskGroup,
    "networks": NetworkResource,
    "devices": RequestedDevice,
    "volume_mounts": VolumeMount,
    "allocated_devices": AllocatedDeviceResource,
    "instances": NodeDeviceInstance,
    "score_meta": NodeScoreMeta,
}
_NESTED_DICTS = {
    "volumes": VolumeRequest,
    "host_volumes": ClientHostVolumeConfig,
    "csi_node_plugins": CSINodeInfo,
    # evals round-trip their structured failure metrics so blocked-eval
    # consumers (`eval status`) keep the per-dimension exhaustion counts
    # and rejection histograms instead of opaque dicts
    "failed_tg_allocs": AllocMetric,
}


def _decode_field(ftype, name, val):
    if name in _NESTED and isinstance(val, dict):
        return _decode_into(_NESTED[name], val)
    if name in _NESTED_DICTS and isinstance(val, dict):
        return {
            k: _decode_into(_NESTED_DICTS[name], v) if isinstance(v, dict) else v
            for k, v in val.items()
        }
    if name in _NESTED_LISTS and isinstance(val, list):
        return [
            _decode_dev(v)
            if name == "devices" and isinstance(v, dict) and "instances" in v
            else _decode_into(_NESTED_LISTS[name], v)
            if isinstance(v, dict)
            else v
            for v in val
        ]
    return val


def _decode_dev(v: dict):
    """Node device groups (with instances) vs task device asks share the
    field name ``devices``; disambiguate by shape."""
    return _decode_into(NodeDeviceResource, v)


def decode_job(data: dict) -> Job:
    return _decode_into(Job, data)


def decode_node(data: dict) -> Node:
    return _decode_into(Node, data)


def decode_alloc(data: dict) -> Allocation:
    known = {f.name for f in dataclasses.fields(Allocation)}
    return Allocation(
        **{
            k: v
            for k, v in data.items()
            if k in known
            and k
            not in (
                "resources",
                "metrics",
                "reschedule_tracker",
                "desired_transition",
                "deployment_status",
            )
        }
    )


def decode_eval(data: dict) -> Evaluation:
    return _decode_into(Evaluation, data)
