"""Python SDK — the api/ package analog.

Reference: api/ (19.5k LoC standalone Go module mirroring every HTTP
endpoint: api.go, jobs.go, nodes.go, allocations.go, evaluations.go,
operator.go). Stdlib urllib transport; one class per noun, hung off
``NomadClient`` exactly like api.Client's accessors."""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import Any, Optional


class APIException(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class NomadClient:
    def __init__(
        self,
        address: str = "http://127.0.0.1:4646",
        timeout: float = 10.0,
        token: str = "",
    ):
        self.address = address.rstrip("/")
        self.timeout = timeout
        # ACL secret (api/api.go SetSecretID; header X-Nomad-Token)
        self.token = token or os.environ.get("NOMAD_TOKEN", "")

    # -- transport ---------------------------------------------------------
    def _request(
        self,
        method: str,
        path: str,
        body: Any = None,
        params: Optional[dict] = None,
    ):
        url = self.address + path
        if params:
            from urllib.parse import urlencode

            url += "?" + urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["X-Nomad-Token"] = self.token
        req = urllib.request.Request(
            url, data=data, method=method, headers=headers
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"null")
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("error", str(e))
            except Exception:  # noqa: BLE001
                msg = str(e)
            raise APIException(e.code, msg) from None

    def get(self, url_path, **params):
        return self._request("GET", url_path, params=params or None)

    def post(self, url_path, body=None, **params):
        return self._request(
            "POST", url_path, body=body, params=params or None
        )

    def delete(self, url_path, **params):
        return self._request("DELETE", url_path, params=params or None)

    # -- nouns -------------------------------------------------------------
    @property
    def jobs(self) -> "Jobs":
        return Jobs(self)

    @property
    def nodes(self) -> "Nodes":
        return Nodes(self)

    @property
    def allocations(self) -> "Allocations":
        return Allocations(self)

    @property
    def evaluations(self) -> "Evaluations":
        return Evaluations(self)

    @property
    def deployments(self) -> "Deployments":
        return Deployments(self)

    @property
    def operator(self) -> "Operator":
        return Operator(self)

    @property
    def agent(self) -> "Agent":
        return Agent(self)

    @property
    def namespaces(self) -> "Namespaces":
        return Namespaces(self)

    @property
    def scaling(self) -> "Scaling":
        return Scaling(self)

    def search(self, prefix: str, context: str = "all",
               namespace: str = "default"):
        return self.post(
            "/v1/search", {"prefix": prefix, "context": context},
            namespace=namespace,
        )

    @property
    def volumes(self) -> "Volumes":
        return Volumes(self)


class Jobs:
    def __init__(self, c: NomadClient):
        self.c = c

    def list(self):
        return self.c.get("/v1/jobs")

    def register(self, job_dict: dict):
        return self.c.post("/v1/jobs", {"job": job_dict})

    def plan(self, job_dict: dict):
        return self.c.post(f"/v1/job/{job_dict['id']}/plan", {"job": job_dict})

    def info(self, job_id: str, namespace: str = "default"):
        return self.c.get(f"/v1/job/{job_id}", namespace=namespace)

    def deregister(self, job_id: str, namespace: str = "default"):
        return self.c.delete(f"/v1/job/{job_id}", namespace=namespace)

    def allocations(self, job_id: str, namespace: str = "default"):
        return self.c.get(f"/v1/job/{job_id}/allocations", namespace=namespace)

    def evaluations(self, job_id: str, namespace: str = "default"):
        return self.c.get(f"/v1/job/{job_id}/evaluations", namespace=namespace)

    def summary(self, job_id: str, namespace: str = "default"):
        return self.c.get(f"/v1/job/{job_id}/summary", namespace=namespace)

    def dispatch(
        self, job_id: str, payload: bytes = b"", meta=None, namespace: str = "default"
    ):
        import base64

        return self.c.post(
            f"/v1/job/{job_id}/dispatch",
            {
                "payload": base64.b64encode(payload).decode(),
                "meta": meta or {},
            },
            namespace=namespace,
        )

    def scale(self, job_id: str, group: str, count: int,
              message: str = "", namespace: str = "default"):
        return self.c.post(
            f"/v1/job/{job_id}/scale",
            {"target": {"group": group}, "count": count, "message": message},
            namespace=namespace,
        )

    def scale_status(self, job_id: str, namespace: str = "default"):
        return self.c.get(f"/v1/job/{job_id}/scale", namespace=namespace)

    def periodic_force(self, job_id: str, namespace: str = "default"):
        return self.c.post(
            f"/v1/job/{job_id}/periodic/force", namespace=namespace
        )


class Nodes:
    def __init__(self, c: NomadClient):
        self.c = c

    def list(self):
        return self.c.get("/v1/nodes")

    def info(self, node_id: str):
        return self.c.get(f"/v1/node/{node_id}")

    def drain(self, node_id: str, enabled: bool = True, deadline_s: float = 3600):
        return self.c.post(
            f"/v1/node/{node_id}/drain",
            {"drain_enabled": enabled, "deadline_s": deadline_s},
        )

    def eligibility(self, node_id: str, eligible: bool):
        return self.c.post(
            f"/v1/node/{node_id}/eligibility",
            {"eligibility": "eligible" if eligible else "ineligible"},
        )

    def allocations(self, node_id: str):
        return self.c.get(f"/v1/node/{node_id}/allocations")


class Allocations:
    def __init__(self, c: NomadClient):
        self.c = c

    def list(self):
        return self.c.get("/v1/allocations")

    def info(self, alloc_id: str):
        return self.c.get(f"/v1/allocation/{alloc_id}")

    def explain(self, alloc_id: str):
        """Score provenance: why this alloc landed on its node
        (`nomad-tpu alloc why`)."""
        return self.c.get(f"/v1/allocations/{alloc_id}/explain")

    def fs_ls(self, alloc_id: str, fs_path: str = "/"):
        return self.c.get(
            f"/v1/client/fs/ls/{alloc_id}", **{"path": fs_path}
        )

    def fs_cat(self, alloc_id: str, fs_path: str, offset: int = 0,
               limit: int = 1 << 20):
        return self.c.get(
            f"/v1/client/fs/cat/{alloc_id}",
            **{"path": fs_path, "offset": offset, "limit": limit},
        )["data"]

    def logs(self, alloc_id: str, task: str, type: str = "stdout",
             follow: bool = False, offset: int = 0):
        """Iterate log frames ({'offset': n, 'data': str}); with
        ``follow`` streams until the connection closes (api/fs.go Logs)."""
        import urllib.error
        import urllib.request
        from urllib.parse import urlencode

        params = urlencode({
            "task": task, "type": type,
            "follow": "true" if follow else "false", "offset": offset,
        })
        url = (
            f"{self.c.address}/v1/client/fs/logs/{alloc_id}?{params}"
        )
        req = urllib.request.Request(url)
        try:
            resp = urllib.request.urlopen(
                req, timeout=None if follow else self.c.timeout
            )
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("error", str(e))
            except Exception:  # noqa: BLE001
                msg = str(e)
            raise APIException(e.code, msg) from None

        def gen():
            import json as _json

            with resp:
                for line in resp:
                    line = line.strip()
                    if line:
                        yield _json.loads(line)

        return gen()


class Evaluations:
    def __init__(self, c: NomadClient):
        self.c = c

    def list(self):
        return self.c.get("/v1/evaluations")

    def info(self, eval_id: str):
        return self.c.get(f"/v1/evaluation/{eval_id}")

    def placement(self, eval_id: str):
        """Per-task-group placement explanation (candidate table +
        rejection histogram) for one eval."""
        return self.c.get(f"/v1/evaluations/{eval_id}/placement")


class Deployments:
    def __init__(self, c: NomadClient):
        self.c = c

    def list(self):
        return self.c.get("/v1/deployments")

    def info(self, deployment_id: str):
        return self.c.get(f"/v1/deployment/{deployment_id}")

    def for_job(self, job_id: str, namespace: str = "default"):
        return self.c.get(f"/v1/job/{job_id}/deployments", namespace=namespace)

    def promote(self, deployment_id: str):
        return self.c.post(f"/v1/deployment/promote/{deployment_id}")

    def pause(self, deployment_id: str, pause: bool = True):
        return self.c.post(
            f"/v1/deployment/pause/{deployment_id}", {"pause": pause}
        )

    def fail(self, deployment_id: str):
        return self.c.post(f"/v1/deployment/fail/{deployment_id}")


class Operator:
    def __init__(self, c: NomadClient):
        self.c = c

    def scheduler_config(self):
        return self.c.get("/v1/operator/scheduler/configuration")

    def snapshot_save(self, path: str):
        return self.c.post("/v1/operator/snapshot/save", {"path": path})

    def set_scheduler_config(self, **kwargs):
        return self.c.post("/v1/operator/scheduler/configuration", kwargs)


class Volumes:
    """CSI volumes (api/csi.go analog)."""

    def __init__(self, c: NomadClient):
        self.c = c

    def list(self):
        return self.c.get("/v1/volumes")

    def info(self, volume_id: str):
        return self.c.get(f"/v1/volume/csi/{volume_id}")

    def register(self, volume_dict: dict):
        return self.c.post(
            f"/v1/volume/csi/{volume_dict['id']}", volume_dict
        )

    def deregister(self, volume_id: str, force: bool = False):
        params = {"force": "true"} if force else {}
        return self.c.delete(f"/v1/volume/csi/{volume_id}", **params)

    def plugins(self):
        return self.c.get("/v1/plugins")


class Agent:
    def __init__(self, c: NomadClient):
        self.c = c

    def self(self):
        return self.c.get("/v1/agent/self")

    def metrics(self):
        return self.c.get("/v1/metrics")


class Namespaces:
    def __init__(self, c: NomadClient):
        self.c = c

    def list(self):
        return self.c.get("/v1/namespaces")

    def info(self, name: str):
        return self.c.get(f"/v1/namespace/{name}")

    def apply(self, name: str, description: str = ""):
        return self.c.post(
            f"/v1/namespace/{name}", {"description": description}
        )

    def delete(self, name: str):
        return self.c.delete(f"/v1/namespace/{name}")


class Scaling:
    def __init__(self, c: NomadClient):
        self.c = c

    def policies(self, namespace: str = "default"):
        return self.c.get("/v1/scaling/policies", namespace=namespace)
