"""nomad_tpu.analysis — repo-specific static analysis & runtime checkers.

Three engines behind one CLI (``python -m nomad_tpu.analysis``) and one
fast pytest entry point (tests/test_static_analysis.py):

- ``lint``    — an AST visitor framework plus repo-specific rules
  (NTA001–NTA008) that encode the invariants the north star depends on
  but the test suite cannot see: trace-pure device kernels, deterministic
  scheduler scoring, observable exception handling, frozen plans after
  submission, class-level lock discipline, the worker batch path's
  merged-submit discipline, and injectable-clock time in broker/server
  scheduling paths (so chaos skew faults and replay can steer them).
- ``race``    — an env-gated (``NOMAD_TPU_RACECHECK=1``) instrumented
  ``threading.Lock``/``RLock`` wrapper that records per-thread lock
  acquisition order, builds the global lock graph, and reports cycles
  (deadlock potential) and guarded-field accesses without the owning
  lock.
- ``retrace`` — a jit-retrace budget checker over the trace counters the
  ``utils.backend.traced_jit`` wrapper maintains for the hot-path device
  kernels; a kernel that silently retraces past its declared budget
  across a bench batch fails the check.

Lint findings diff against the checked-in ``analysis/baseline.json``:
pre-existing violations are ratcheted (they stay visible and must not
grow), new ones fail the run. ``--fix-baseline`` regenerates the file
deterministically (sorted, path-relative).
"""

from . import lint, race, retrace  # noqa: F401

__all__ = ["lint", "race", "retrace"]
