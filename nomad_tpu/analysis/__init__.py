"""nomad_tpu.analysis — repo-specific static analysis & runtime checkers.

Four engines behind one CLI (``python -m nomad_tpu.analysis``) and two
fast pytest entry points (tests/test_static_analysis.py,
tests/test_jaxlint.py):

- ``lint``    — an AST visitor framework plus repo-specific rules
  (NTA001–NTA008) that encode the invariants the north star depends on
  but the test suite cannot see: trace-pure device kernels, deterministic
  scheduler scoring, observable exception handling, frozen plans after
  submission, class-level lock discipline, the worker batch path's
  merged-submit discipline, and injectable-clock time in broker/server
  scheduling paths (so chaos skew faults and replay can steer them).
- ``race``    — an env-gated (``NOMAD_TPU_RACECHECK=1``) instrumented
  ``threading.Lock``/``RLock`` wrapper that records per-thread lock
  acquisition order, builds the global lock graph, and reports cycles
  (deadlock potential) and guarded-field accesses without the owning
  lock.
- ``retrace`` — a jit-retrace budget checker over the trace counters the
  ``utils.backend.traced_jit`` wrapper maintains for the hot-path device
  kernels; a kernel that silently retraces past its declared budget
  across a bench batch fails the check.
- ``jaxlint`` — static analysis over the *traced* kernel fleet: every
  ``traced_jit`` kernel is re-traced abstractly from its recorded call
  specs and its ClosedJaxpr checked for host callbacks, baked host
  constants, dtype/weak-type leaks, nondeterministic primitives, and
  retrace hazards (JXL001–JXL005), plus canonical jaxpr fingerprints
  and the mesh/explain invariance differ (JXL006). Kept jax-free at
  import: ``python -m nomad_tpu.analysis --source-only`` never touches
  jax.

Lint findings diff against the checked-in baselines
(``analysis/baseline.json`` for source rules,
``analysis/jaxlint/baseline.json`` for jaxpr rules): pre-existing
violations are ratcheted (they stay visible and must not grow), new
ones fail the run. ``--fix-baseline`` regenerates both files
deterministically (sorted, path-relative).
"""

from . import lint, race, retrace  # noqa: F401

__all__ = ["jaxlint", "lint", "race", "retrace"]


def __getattr__(name):
    # lazy: jaxlint pulls in jax at analysis time, and plain
    # `import nomad_tpu.analysis` (the source lint path) must not
    if name == "jaxlint":
        from . import jaxlint

        return jaxlint
    raise AttributeError(name)
