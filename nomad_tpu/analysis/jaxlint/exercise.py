"""Drive the production kernel fleet once with a tiny synthetic workload.

The analyzer re-traces kernels from their *recorded* call specs
(utils/backend kernel registry); a kernel that has never been called in
this process has no spec. This module is the standalone driver: a
16-node cluster and a handful of asks routed so every production kernel
traces exactly once — the four PlacementKernel families through the
real dispatch (closed-form, exact scan, chunked, one-per-value), the
score-matrix kernel in both its class-less and throughput configs, the
two preemption kernels, the hetero joint kernel, and the cp auction.

Shapes are deliberately minimal: the analyzer checks program structure,
not numerics, and a full fleet exercise compiles in seconds on CPU.
Everything is seeded/deterministic so the recorded specs — and
therefore the fingerprint table — are a pure function of this file.
"""

from __future__ import annotations

N_NODES = 16
D = 4


def _cluster():
    import numpy as np

    from ...device.flatten import ClusterTensors, node_bucket

    pn = node_bucket(N_NODES)
    capacity = np.zeros((pn, D), dtype=np.float32)
    capacity[:N_NODES, 0] = 16000.0
    capacity[:N_NODES, 1] = 32768.0
    capacity[:N_NODES, 2] = 100 * 1024.0
    capacity[:N_NODES, 3] = 1000.0
    used = np.zeros_like(capacity)
    used[:N_NODES, :2] = capacity[:N_NODES, :2] * 0.1
    ready = np.zeros(pn, dtype=bool)
    ready[:N_NODES] = True
    return ClusterTensors(
        node_ids=[f"jxl-node-{i}" for i in range(N_NODES)],
        index=1,
        num_nodes=N_NODES,
        capacity=capacity,
        used=used,
        ready=ready,
        dc_ids=np.zeros(pn, dtype=np.int32),
        class_ids=np.zeros(pn, dtype=np.int32),
        dc_vocab={"dc1": 0},
        class_vocab={"small": 0},
        class_rep=[0],
        node_row={f"jxl-node-{i}": i for i in range(N_NODES)},
    )


def _ask(ct, job, count, blocks=None):
    import numpy as np

    from ...device.flatten import GroupAsk

    pn = ct.padded_n
    return GroupAsk(
        job_id=f"jxl-{job}",
        tg_name="web",
        count=count,
        desired_total=count,
        ask=np.array([250.0, 512.0, 300.0, 0.0], dtype=np.float32),
        eligible=ct.ready.copy(),
        job_counts=np.zeros(pn, dtype=np.int32),
        penalty_nodes=np.zeros(pn, dtype=bool),
        affinity_scores=np.zeros(pn, dtype=np.float32),
        has_affinities=False,
        distinct_hosts=False,
        blocks=blocks,
    )


def _blocks(ct, kind, values=4):
    """One spread/cap accounting block over a synthetic rack attribute."""
    import numpy as np

    from ...device.flatten import ValueBlocks

    pn = ct.padded_n
    value_ids = np.full((1, pn), -1, dtype=np.int32)
    value_ids[0, :N_NODES] = np.arange(N_NODES) % values
    return ValueBlocks(
        value_ids=value_ids,
        counts0=np.zeros((1, values), dtype=np.float32),
        desired=np.full((1, values), -1.0, dtype=np.float32),
        caps=np.full((1, values), np.inf, dtype=np.float32),
        weights=np.ones(1, dtype=np.float32),
        kinds=np.array([kind], dtype=np.int32),
    )


def run_placement_paths(
    explain: bool = False, incremental: bool = False
) -> int:
    """Route one tiny batch through each PlacementKernel family.
    Returns the number of placement results produced.

    With ``incremental`` a DeviceStateCache rides along as the cluster's
    score cache and the batch runs TWICE — full rebuild, then one
    churned row through the dirty-patch path with a generation swap
    between — so the differ observes every incremental code path while
    proving none of them traced a new program."""
    from ...device.score import (
        BLOCK_EVEN_SPREAD,
        BLOCK_TARGET_SPREAD,
        PlacementKernel,
    )

    ct = _cluster()
    cache = None
    if incremental:
        from ...device.cache import DeviceStateCache

        cache = DeviceStateCache()
        ct.score_cache = cache
    asks = [
        _ask(ct, "fast-a", 3),  # closed-form top-k
        _ask(ct, "fast-b", 2),
        _ask(ct, "scan", 3, blocks=_blocks(ct, BLOCK_TARGET_SPREAD)),
        _ask(ct, "chunked", 40, blocks=_blocks(ct, BLOCK_TARGET_SPREAD)),
        _ask(ct, "opv", 40, blocks=_blocks(ct, BLOCK_EVEN_SPREAD)),
    ]
    kernel = PlacementKernel("binpack")
    results = kernel.place(ct, asks, explain=explain)
    if cache is not None:
        cache.score_commit()
        ct.used[0, 0] += 128.0  # one dirty row → per-shard patch pass
        results = kernel.place(ct, asks, explain=explain)
        cache.score_commit()
    return sum(1 for r in results if r is not None)


def run_score_matrix() -> None:
    """score_matrix_kernel in both configs: class-less (throughputs
    None — the Python gate) and with the throughput axis."""
    import numpy as np

    from ...device.score import score_matrix_kernel

    g, n = 2, N_NODES
    capacity = np.full((n, D), 16000.0, dtype=np.float32)
    used = capacity * 0.1
    asks = np.full((g, D), 250.0, dtype=np.float32)
    eligible = np.ones((g, n), dtype=bool)
    job_counts = np.zeros((g, n), dtype=np.int32)
    desired_totals = np.full(g, 3.0, dtype=np.float32)
    penalty = np.zeros((g, n), dtype=bool)
    affinity = np.zeros((g, n), dtype=np.float32)
    has_aff = np.zeros(g, dtype=bool)
    distinct = np.zeros(g, dtype=bool)
    spread = np.asarray(False)
    score_matrix_kernel(
        capacity, used, asks, eligible, job_counts, desired_totals,
        penalty, affinity, has_aff, distinct, spread,
    )
    tp = np.ones((g, n), dtype=np.float32)
    score_matrix_kernel(
        capacity, used, asks, eligible, job_counts, desired_totals,
        penalty, affinity, has_aff, distinct, spread, tp,
    )


def run_preemption() -> None:
    import numpy as np

    from ...device.preempt import (
        choose_preemption_node_kernel,
        find_preemption_kernel,
    )

    n, v = N_NODES, 3
    capacity = np.full((n, D), 16000.0, dtype=np.float32)
    used = capacity * 0.9
    ask = np.array([4000.0, 8000.0, 100.0, 0.0], dtype=np.float32)
    eligible = np.ones(n, dtype=bool)
    rng = np.random.default_rng(11)
    victim_res = rng.uniform(
        100.0, 4000.0, size=(n, v, D)
    ).astype(np.float32)
    victim_prio = np.full((n, v), 20, dtype=np.int32)
    victim_mask = np.ones((n, v), dtype=bool)
    find_preemption_kernel(
        capacity, used, ask, eligible, victim_res, victim_prio,
        victim_mask,
    )
    choose_preemption_node_kernel(
        capacity, used, ask, eligible, victim_res, victim_prio,
        victim_mask,
    )


def run_hetero(policy: int = 0) -> None:
    import numpy as np

    from ...scheduler.hetero import hetero_place_kernel

    g, n = 2, N_NODES
    capacity = np.full((n, D), 16000.0, dtype=np.float32)
    used0 = capacity * 0.1
    asks = np.full((g, D), 250.0, dtype=np.float32)
    counts = np.full(g, 2, dtype=np.int32)
    eligible = np.ones((g, n), dtype=bool)
    tp = np.ones((g, n), dtype=np.float32)
    tpmax = np.ones(g, dtype=np.float32)
    cost = np.ones(n, dtype=np.float32)
    hetero_place_kernel(
        capacity, used0, asks, counts, eligible, tp, tpmax, cost,
        policy=policy, steps=8, max_c=4,
    )


def run_cp() -> None:
    import numpy as np

    from ...device.cp import cp_place_kernel

    g, n = 2, N_NODES
    capacity = np.full((n, D), 16000.0, dtype=np.float32)
    used0 = capacity * 0.1
    asks = np.full((g, D), 250.0, dtype=np.float32)
    counts = np.full(g, 2, dtype=np.int32)
    eligible = np.ones((g, n), dtype=bool)
    scores = np.linspace(
        0.1, 0.9, g * n, dtype=np.float32
    ).reshape(g, n)
    prio = np.full(g, 50.0, dtype=np.float32)
    job_counts = np.zeros((g, n), dtype=np.int32)
    distinct = np.zeros(g, dtype=bool)
    jobgrp = np.arange(g, dtype=np.int32)
    lam0 = np.zeros(n, dtype=np.float32)
    cp_place_kernel(
        capacity, used0, asks, counts, eligible, scores, prio,
        job_counts, distinct, jobgrp, lam0, steps=8, max_c=4,
    )


def run_cp_gang() -> None:
    import numpy as np

    from ...device.cp import cp_gang_place_kernel

    g, n, levels = 2, N_NODES, 4
    capacity = np.full((n, D), 16000.0, dtype=np.float32)
    used0 = capacity * 0.1
    asks = np.full((g, D), 250.0, dtype=np.float32)
    counts = np.full(g, 2, dtype=np.int32)
    eligible = np.ones((g, n), dtype=bool)
    scores = np.linspace(
        0.1, 0.9, g * n, dtype=np.float32
    ).reshape(g, n)
    prio = np.full(g, 50.0, dtype=np.float32)
    job_counts = np.zeros((g, n), dtype=np.int32)
    distinct = np.zeros(g, dtype=bool)
    jobgrp = np.zeros(g, dtype=np.int32)
    gang = np.ones(g, dtype=np.int32)  # both groups in gang 1
    w_rack = np.full(g, 1.0, dtype=np.float32)
    w_pod = np.zeros(g, dtype=np.float32)
    w_ici = np.full(g, 0.5, dtype=np.float32)
    rack_oh = np.zeros((n, levels), dtype=np.int32)
    rack_oh[np.arange(n), 1 + np.arange(n) % (levels - 1)] = 1
    pod_oh = np.zeros((n, 2), dtype=np.int32)
    pod_oh[:, 1] = 1
    ici_oh = np.zeros((n, levels * 2), dtype=np.int32)
    ici_oh[np.arange(n), 1 + np.arange(n) % (levels * 2 - 1)] = 1
    lam0 = np.zeros(n, dtype=np.float32)
    cp_gang_place_kernel(
        capacity, used0, asks, counts, eligible, scores, prio,
        job_counts, distinct, jobgrp, gang, w_rack, w_pod, w_ici,
        rack_oh, pod_oh, ici_oh, lam0, steps=8, max_c=4,
    )


def run_migrate() -> None:
    """migrate_plan_kernel: the defrag plane's bounded-budget move
    selection over a small fragmented fleet."""
    import numpy as np

    from ...device.migrate import migrate_plan_kernel

    a, n = 4, N_NODES
    capacity = np.full((n, D), 16000.0, dtype=np.float32)
    used0 = capacity * 0.2
    sizes = np.full((a, D), 500.0, dtype=np.float32)
    cur = (np.arange(a) % n).astype(np.int32)
    eligible = np.ones((a, n), dtype=bool)
    scores = np.linspace(
        0.1, 0.9, a * n, dtype=np.float32
    ).reshape(a, n)
    cur_scores = scores[np.arange(a), cur]
    move_cost = np.full(a, 0.05, dtype=np.float32)
    lam0 = np.zeros(n, dtype=np.float32)
    migrate_plan_kernel(
        capacity, used0, sizes, cur, eligible, scores, cur_scores,
        move_cost, np.int32(2), lam0, steps=8,
    )


def exercise_fleet(explain: bool = False) -> dict:
    """Run the whole fleet exercise; returns the kernel registry
    afterwards (every production kernel now has a recorded spec)."""
    from ...utils import backend
    from .retracer import import_fleet

    import_fleet()
    run_placement_paths(explain=explain)
    run_score_matrix()
    run_preemption()
    run_hetero()
    run_cp()
    run_cp_gang()
    run_migrate()
    return backend.kernel_registry()
