"""Analyzer driver: exercise the fleet, re-trace, run JXL rules, ratchet.

Mirrors the NTA source-lint engine (``analysis.lint``): findings carry
line-free fingerprints, ``baseline.json`` next to this module is the
accepted-debt ledger, new findings fail, fixed findings are pruned with
``--fix-baseline``. The two engines share the Finding type and baseline
format so ``python -m nomad_tpu.analysis`` can combine them in one run.
"""

from __future__ import annotations

from pathlib import Path

from ..lint import (
    Finding,
    diff_against_baseline,
    load_baseline,
    write_baseline,
)
from . import retracer, rules


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def analyze_kernels(
    registry=None, exercise: bool = True
) -> tuple[list[Finding], dict]:
    """Run JXL001-JXL005 over every production kernel.

    Returns ``(findings, reports)`` where ``reports`` maps kernel name to
    a per-kernel dict: registry metadata (``describe()``), the configs
    analyzed, and finding counts. When ``exercise`` is true and a
    production kernel has no recorded spec yet, the synthetic fleet
    workload (``jaxlint.exercise``) runs first so a cold process — the
    CLI, a fresh pytest worker — still sees the whole fleet.
    """
    from ...utils import backend

    if registry is None:
        registry = retracer.import_fleet()
    prod = retracer.production_kernels(registry)
    if exercise and any(not e.specs for e in prod.values()):
        from .exercise import exercise_fleet

        registry = exercise_fleet()
        prod = retracer.production_kernels(registry)

    findings: list[Finding] = []
    reports: dict[str, dict] = {}
    for name, entry in prod.items():
        report = entry.describe()
        report["configs"] = []
        kernel_findings: list[Finding] = []
        if not entry.specs:
            kernel_findings.append(rules._finding(
                entry, "JXL005",
                "kernel registered but never called: no recorded spec to "
                "analyze — add it to the exercise workload",
            ))
        for sig in list(entry.specs):
            label = retracer.spec_label(entry, sig)
            report["configs"].append(label)
            try:
                closed = retracer.retrace(entry, entry.specs[sig])
            except retracer.UnretraceableSpec as e:
                kernel_findings.append(rules._finding(
                    entry, "JXL005", f"unretraceable spec ({e}) — the "
                    "analyzer cannot audit this config",
                ))
                continue
            kernel_findings.extend(rules.check_kernel(entry, closed))
        if entry.specs:
            # registry-level findings are per-kernel, not per-config;
            # check_kernel appended them once per spec — dedupe
            seen: set[str] = set()
            unique = []
            for f in sorted(
                kernel_findings, key=lambda f: (f.rule, f.message)
            ):
                if f.fingerprint not in seen:
                    seen.add(f.fingerprint)
                    unique.append(f)
            kernel_findings = unique
        report["findings"] = len(kernel_findings)
        reports[name] = report
        findings.extend(kernel_findings)
    findings.sort(key=lambda f: (f.path, f.symbol, f.rule, f.message))
    return findings, reports


def run_jaxlint(
    baseline_path: Path | None = None,
    fix_baseline: bool = False,
) -> tuple[int, list[Finding], set[str], dict]:
    """Full ratcheted run. Returns (exit_code, new_findings,
    fixed_fingerprints, per-kernel reports)."""
    path = baseline_path or default_baseline_path()
    findings, reports = analyze_kernels()
    baseline = load_baseline(path)
    new, fixed = diff_against_baseline(findings, baseline)
    if fix_baseline:
        write_baseline(findings, path)
        return 0, new, fixed, reports
    return (1 if new else 0), new, fixed, reports
