"""JXL001-JXL005 — rules over the traced device-kernel fleet.

Unlike the NTA source rules (AST walks over Python files), these walk
the ClosedJaxpr the analyzer re-traced from each kernel's recorded call
spec — the program XLA actually compiles. Findings reuse the NTA
``lint.Finding`` shape (and therefore the same line-number-free
fingerprint ratchet): ``path`` is the kernel's defining module,
``symbol`` is the kernel name, so a finding survives unrelated edits
and leaves the baseline only when the traced program changes.

Rule set:

- **JXL001 host-callback purity** — no ``pure_callback`` /
  ``io_callback`` / ``debug_callback`` primitives in production
  kernels. A callback re-enters Python per executed step: it wedges
  under the watchdog's poisoned-thread handoff, dies inside a donated
  buffer, and silently serializes the batch.
- **JXL002 transfer hygiene** — no large host constants baked into the
  jaxpr. A closure-captured array becomes a ``const`` re-uploaded with
  every compiled executable instead of flowing through the
  ``shard_put`` seam as a sharded argument (NTA015 is the source-level
  half of this check; JXL002 sees what tracing actually captured).
- **JXL003 dtype discipline** — no f64/c128/x64 avals and no
  weak-typed kernel outputs. The byte-parity oracles compare uint32
  views of f32 buffers; a weak output or a 64-bit promotion changes
  width with ambient x64 config and breaks them bitwise.
- **JXL004 nondeterministic primitives** — no unordered multi-index
  scatter accumulation (``scatter-add``/``mul``/``min``/``max`` with
  ``unique_indices=False`` over >1 update) and no unstable sorts.
  Their accumulation/tie order is implementation-defined, which breaks
  bitwise reproducibility across backends.
- **JXL005 retrace-hazard audit** — closure-captured Python scalars
  (they bake silently into the trace: change the value, keep the
  cache entry), declared static_argnames that don't exist in the
  signature, and kernels with no declared retrace budget (the budget
  checker in ``analysis.retrace`` can't see them).
"""

from __future__ import annotations

import inspect

from ..lint import Finding

# JXL001: primitives that re-enter Python from inside the program
CALLBACK_PRIMS = frozenset(
    {"pure_callback", "io_callback", "debug_callback", "outside_call"}
)

# JXL002: consts at or under this element count are scalars/lookup
# tables legitimately baked by tracing (iota seeds, clamp bounds);
# anything bigger is cluster-shaped data that must arrive as an arg
CONST_ELEMS_MAX = 64

# JXL003: dtypes that can't round-trip a uint32-view byte-parity oracle
WIDE_DTYPES = frozenset({"float64", "complex128", "int64", "uint64"})

# JXL004: scatter variants whose multi-update accumulation order is
# implementation-defined for floats
UNORDERED_SCATTERS = frozenset(
    {"scatter-add", "scatter-mul", "scatter-min", "scatter-max"}
)


def kernel_path(entry) -> str:
    """Repo-relative path of the kernel's defining module."""
    return entry.fn.__module__.replace(".", "/") + ".py"


def kernel_line(entry) -> int:
    try:
        return entry.fn.__code__.co_firstlineno
    except AttributeError:
        return 0


def _finding(entry, rule: str, message: str) -> Finding:
    return Finding(
        rule=rule,
        path=kernel_path(entry),
        line=kernel_line(entry),
        symbol=entry.short,
        message=message,
    )


def iter_eqns(closed):
    """Yield every equation in a ClosedJaxpr, recursing into sub-jaxpr
    params (scan/while/cond bodies, pjit calls, scatter update fns)."""
    stack = [closed.jaxpr]
    while stack:
        jaxpr = stack.pop()
        for eqn in jaxpr.eqns:
            yield eqn
            for v in eqn.params.values():
                stack.extend(_sub_jaxprs(v))


def _sub_jaxprs(v):
    if hasattr(v, "jaxpr"):  # ClosedJaxpr
        return [v.jaxpr]
    if hasattr(v, "eqns"):  # open Jaxpr (e.g. scatter update_jaxpr)
        return [v]
    if isinstance(v, (tuple, list)):
        out = []
        for x in v:
            out.extend(_sub_jaxprs(x))
        return out
    return []


def iter_consts(closed):
    """Yield (const, owner) for the top jaxpr and every sub-jaxpr that
    carries its own consts."""
    for c in closed.consts:
        yield c
    seen = [closed.jaxpr]
    while seen:
        jaxpr = seen.pop()
        for eqn in jaxpr.eqns:
            for v in eqn.params.values():
                for sub in _closed_subs(v):
                    for c in sub.consts:
                        yield c
                    seen.append(sub.jaxpr)


def _closed_subs(v):
    if hasattr(v, "jaxpr"):
        return [v]
    if isinstance(v, (tuple, list)):
        out = []
        for x in v:
            out.extend(_closed_subs(x))
        return out
    return []


# -- jaxpr-level rules -------------------------------------------------------


def check_callback_purity(entry, closed) -> list[Finding]:
    """JXL001"""
    out = []
    seen = set()
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name in CALLBACK_PRIMS and name not in seen:
            seen.add(name)
            out.append(_finding(
                entry, "JXL001",
                f"host callback primitive {name!r} in a production "
                "kernel: the traced program re-enters Python per step — "
                "hoist the host work outside the kernel",
            ))
    return out


def check_transfer_hygiene(entry, closed) -> list[Finding]:
    """JXL002"""
    import numpy as np

    out = []
    for c in iter_consts(closed):
        size = int(np.size(c)) if hasattr(c, "__len__") or hasattr(
            c, "shape"
        ) else 1
        if size > CONST_ELEMS_MAX:
            dt = getattr(c, "dtype", type(c).__name__)
            shp = tuple(getattr(c, "shape", ()))
            out.append(_finding(
                entry, "JXL002",
                f"host constant {dt}{list(shp)} ({size} elems) baked "
                "into the jaxpr: closure-captured arrays re-upload per "
                "executable — pass it as an argument through the "
                "shard_put seam",
            ))
    return out


def check_dtype_discipline(entry, closed) -> list[Finding]:
    """JXL003"""
    out = []
    seen = set()
    for eqn in iter_eqns(closed):
        for v in eqn.outvars:
            dt = str(getattr(v.aval, "dtype", ""))
            if dt in WIDE_DTYPES and dt not in seen:
                seen.add(dt)
                out.append(_finding(
                    entry, "JXL003",
                    f"{dt} intermediate in the traced program: 64-bit "
                    "promotion breaks the uint32-view byte-parity "
                    "oracles — pin the dtype explicitly",
                ))
    for i, v in enumerate(closed.jaxpr.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None and getattr(aval, "weak_type", False):
            out.append(_finding(
                entry, "JXL003",
                f"kernel output {i} is weak-typed "
                f"({getattr(aval, 'dtype', '?')}): its width follows "
                "ambient x64 config — cast explicitly before returning",
            ))
    return out


def check_determinism(entry, closed) -> list[Finding]:
    """JXL004"""
    import numpy as np

    out = []
    flagged = set()
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name in UNORDERED_SCATTERS and not eqn.params.get(
            "unique_indices", True
        ):
            # single-update scatters are order-free regardless of flags
            idx_aval = eqn.invars[1].aval if len(eqn.invars) > 1 else None
            n_updates = (
                int(np.prod(idx_aval.shape[:-1]))
                if idx_aval is not None and len(idx_aval.shape) > 0
                else 1
            )
            if n_updates > 1 and name not in flagged:
                flagged.add(name)
                out.append(_finding(
                    entry, "JXL004",
                    f"{name} over {n_updates} updates with "
                    "unique_indices=False: float accumulation order is "
                    "implementation-defined — sort/segment the indices "
                    "or assert uniqueness",
                ))
        if name == "sort" and not eqn.params.get("is_stable", True):
            if "sort" not in flagged:
                flagged.add("sort")
                out.append(_finding(
                    entry, "JXL004",
                    "unstable sort in the traced program: tie order is "
                    "implementation-defined — use stable=True",
                ))
    return out


# -- registry-level rules ----------------------------------------------------


def check_retrace_hazards(entry) -> list[Finding]:
    """JXL005 — needs no jaxpr: audits the kernel's Python closure and
    declared jit config against the retrace-budget discipline."""
    out = []
    fn = entry.fn
    freevars = getattr(fn.__code__, "co_freevars", ())
    cells = fn.__closure__ or ()
    for name, cell in zip(freevars, cells):
        try:
            val = cell.cell_contents
        except ValueError:  # empty cell
            continue
        if isinstance(val, (bool, int, float, str)):
            out.append(_finding(
                entry, "JXL005",
                f"closure-captured Python scalar {name!r} "
                f"({type(val).__name__}): it bakes into the trace "
                "invisibly to the jit cache — declare it a static "
                "argument instead",
            ))
    try:
        params = set(inspect.signature(fn).parameters)
    except (TypeError, ValueError):
        params = None
    if params is not None:
        for sa in entry.static_argnames:
            if sa not in params:
                out.append(_finding(
                    entry, "JXL005",
                    f"declared static argname {sa!r} is not a parameter "
                    "of the kernel — the jit cache keys on a phantom",
                ))
    if entry.retrace_budget is None:
        out.append(_finding(
            entry, "JXL005",
            "no retrace_budget declared: the retrace budget checker "
            "(analysis.retrace) cannot audit this kernel — declare one",
        ))
    return out


JAXPR_CHECKS = (
    check_callback_purity,
    check_transfer_hygiene,
    check_dtype_discipline,
    check_determinism,
)


def check_kernel(entry, closed) -> list[Finding]:
    """All JXL findings for one kernel's traced program + registry row."""
    findings: list[Finding] = []
    for chk in JAXPR_CHECKS:
        findings.extend(chk(entry, closed))
    findings.extend(check_retrace_hazards(entry))
    findings.sort(key=lambda f: (f.rule, f.message))
    return findings
