"""jaxlint — static analysis over the *traced* device-kernel fleet.

The NTA rules (``analysis.rules``) read Python source; they cannot see
what tracing actually produced. This package closes that gap: the
``utils.backend.traced_jit`` registry keeps every kernel's un-jitted
body and last-seen abstract call specs, the retracer turns a spec back
into a ``ClosedJaxpr`` via ``jax.make_jaxpr`` (no data, no device), and
the JXL rules walk that program:

- JXL001  host-callback purity (no pure/io/debug callbacks)
- JXL002  transfer hygiene (no large host constants baked into the jaxpr)
- JXL003  dtype discipline (no 64-bit avals, no weak-typed outputs)
- JXL004  nondeterministic primitives (unordered scatters, unstable sorts)
- JXL005  retrace hazards (closure scalars, phantom statics, no budget)
- JXL006  canonical jaxpr fingerprints + the invariance differ
          (``jaxlint.diff``): mesh-on/off and explain-on/off proven
          fleet-wide as fingerprint equalities

Findings ratchet against ``jaxlint/baseline.json`` exactly like the
source lint. Run via ``python -m nomad_tpu.analysis`` (combined) or
``nomad-tpu analyze kernels``.
"""

from .engine import analyze_kernels, default_baseline_path, run_jaxlint
from .fingerprint import (
    canonical_text,
    fingerprint,
    fingerprint_table,
    reset_fingerprint_cache,
)
from .retracer import UnretraceableSpec, import_fleet, retrace

__all__ = [
    "UnretraceableSpec",
    "analyze_kernels",
    "canonical_text",
    "default_baseline_path",
    "fingerprint",
    "fingerprint_table",
    "import_fleet",
    "reset_fingerprint_cache",
    "retrace",
    "run_jaxlint",
]
