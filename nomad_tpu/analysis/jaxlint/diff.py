"""JXL006 differ — prove whole-fleet jaxpr-identity claims.

The repo's perf story leans on several "this feature does not change the
compiled program" claims: mesh sharding placement (the jaxpr is a pure
function of avals + declared statics; the ambient mesh must not leak
into tracing), placement explainability (``explain=`` is a host-side
gate, never a second jitted program), and the class-less throughput gate
(``throughputs=None`` routes to the same base program). Before this
module those were scattered per-test spot checks; here they are proven
fleet-wide by re-tracing every recorded kernel config under both ambient
states and comparing canonical fingerprints.

Each prover returns a report dict (per-kernel, per-config fingerprints
on both sides plus an overall ``ok``) rather than asserting, so the CLI
can print it and tests can pin it.
"""

from __future__ import annotations

import os

from .fingerprint import fingerprint
from . import retracer

_MESH_ENV = "NOMAD_TPU_MESH"
_INCR_ENV = "NOMAD_TPU_INCREMENTAL"


def _fingerprints_here(entries) -> dict:
    """{kernel short: {sig: fp}} re-traced under the CURRENT ambient
    state. Deliberately bypasses the fingerprint cache — the point is to
    observe what tracing does right now."""
    out: dict = {}
    for entry in entries:
        per = {}
        for sig, spec in entry.specs.items():
            per[sig] = fingerprint(retracer.retrace(entry, spec))
        out[entry.short] = per
    return out


def prove_mesh_invariance(registry=None) -> dict:
    """Re-trace every recorded production config with the mesh forced
    off and forced on, and compare fingerprints.

    Proves the ambient mesh cannot leak into a traced program: sharding
    enters only through explicitly declared statics (``n_shards`` rows
    appear as their own configs in the fingerprint table) and input
    shardings, never by changing the jaxpr. Needs >1 visible device to
    actually activate a mesh; reports ``skipped`` otherwise.
    """
    import jax

    from ...utils import backend

    if registry is None:
        registry = retracer.import_fleet()
    entries = [
        e for e in retracer.production_kernels(registry).values()
        if e.specs
    ]
    if len(jax.devices()) <= 1:
        return {
            "claim": "mesh-on/off jaxpr equality",
            "ok": True,
            "skipped": "needs >1 visible device to activate a mesh",
            "kernels": {},
        }
    prev = os.environ.get(_MESH_ENV)
    try:
        os.environ[_MESH_ENV] = "off"
        backend.reset_mesh()
        fps_off = _fingerprints_here(entries)
        os.environ[_MESH_ENV] = "auto"
        backend.reset_mesh()
        mesh_shape = [backend.get_mesh().dp, backend.get_mesh().mp]
        fps_on = _fingerprints_here(entries)
    finally:
        if prev is None:
            os.environ.pop(_MESH_ENV, None)
        else:
            os.environ[_MESH_ENV] = prev
        backend.reset_mesh()
    kernels: dict = {}
    ok = True
    by_short = {e.short: e for e in entries}
    for short in sorted(fps_off):
        rows = {}
        for sig in fps_off[short]:
            label = retracer.spec_label(by_short[short], sig)
            equal = fps_off[short][sig] == fps_on[short][sig]
            ok = ok and equal
            rows[label] = {
                "mesh_off": fps_off[short][sig],
                "mesh_on": fps_on[short][sig],
                "equal": equal,
            }
        kernels[short] = rows
    return {
        "claim": "mesh-on/off jaxpr equality",
        "ok": ok,
        "mesh_shape": mesh_shape,
        "kernels": kernels,
    }


def prove_explain_invariance() -> dict:
    """Run the placement exercise with ``explain=False`` then
    ``explain=True`` and prove the explain path added no traced program:
    zero new XLA traces, zero new recorded specs, and every config's
    fingerprint unchanged.
    """
    from ...utils import backend
    from .exercise import run_placement_paths

    registry = retracer.import_fleet()
    run_placement_paths(explain=False)
    entries = [
        e for e in retracer.production_kernels(registry).values()
        if e.specs
    ]
    specs_before = {e.short: set(e.specs) for e in entries}
    traces_before = backend.trace_counts()
    fps_before = _fingerprints_here(entries)

    run_placement_paths(explain=True)
    traces_after = backend.trace_counts()
    fps_after = _fingerprints_here(entries)

    kernels: dict = {}
    ok = True
    for e in entries:
        added_specs = sorted(set(e.specs) - specs_before[e.short])
        added_traces = traces_after.get(e.name, 0) - traces_before.get(
            e.name, 0
        )
        fp_equal = fps_before[e.short] == {
            s: fps_after[e.short][s] for s in specs_before[e.short]
        }
        kernel_ok = not added_specs and added_traces == 0 and fp_equal
        ok = ok and kernel_ok
        kernels[e.short] = {
            "added_specs": added_specs,
            "added_traces": added_traces,
            "fingerprints_equal": fp_equal,
            "ok": kernel_ok,
        }
    return {
        "claim": "explain-on/off adds no traced program",
        "ok": ok,
        "kernels": kernels,
    }


def prove_incremental_invariance() -> dict:
    """Run the placement exercise with the incremental score cache off,
    then on (two passes: full rebuild + one dirty-row patch with a
    generation swap between), and prove the incremental path added no
    traced program: zero new XLA traces, zero new recorded specs, every
    config's fingerprint unchanged. This is the jaxpr half of the
    bit-identity pin — the cached device buffer feeds the kernel with
    the same aval as a from-scratch ``shard_put``, so on and off trace
    the identical kernel set.
    """
    from ...utils import backend
    from .exercise import run_placement_paths

    registry = retracer.import_fleet()
    run_placement_paths(incremental=False)
    entries = [
        e for e in retracer.production_kernels(registry).values()
        if e.specs
    ]
    specs_before = {e.short: set(e.specs) for e in entries}
    traces_before = backend.trace_counts()
    fps_before = _fingerprints_here(entries)

    prev = os.environ.get(_INCR_ENV)
    try:
        os.environ[_INCR_ENV] = "on"
        backend.reset_incremental()
        run_placement_paths(incremental=True)
    finally:
        if prev is None:
            os.environ.pop(_INCR_ENV, None)
        else:
            os.environ[_INCR_ENV] = prev
        backend.reset_incremental()
    traces_after = backend.trace_counts()
    fps_after = _fingerprints_here(entries)

    kernels: dict = {}
    ok = True
    for e in entries:
        added_specs = sorted(set(e.specs) - specs_before[e.short])
        added_traces = traces_after.get(e.name, 0) - traces_before.get(
            e.name, 0
        )
        fp_equal = fps_before[e.short] == {
            s: fps_after[e.short][s] for s in specs_before[e.short]
        }
        kernel_ok = not added_specs and added_traces == 0 and fp_equal
        ok = ok and kernel_ok
        kernels[e.short] = {
            "added_specs": added_specs,
            "added_traces": added_traces,
            "fingerprints_equal": fp_equal,
            "ok": kernel_ok,
        }
    return {
        "claim": "incremental-on/off adds no traced program",
        "ok": ok,
        "kernels": kernels,
    }


def prove_all() -> dict:
    """All fleet invariants; ``ok`` is the conjunction. The full fleet
    exercise runs between the provers so the mesh differ covers every
    production kernel (hetero, cp, preemption, score-matrix), not just
    the placement paths the explain and incremental provers drive."""
    from .exercise import exercise_fleet

    explain = prove_explain_invariance()
    incremental = prove_incremental_invariance()
    mesh = prove_mesh_invariance(exercise_fleet())
    return {
        "ok": explain["ok"] and incremental["ok"] and mesh["ok"],
        "explain": explain,
        "incremental": incremental,
        "mesh": mesh,
    }
