"""JXL006 — canonical jaxpr fingerprints.

A fingerprint is a sha256 over a canonical rendering of a ClosedJaxpr:
variables renamed in first-use order, equations serialized as
(primitive, sorted normalized params, input slots, output avals),
sub-jaxprs (scan/while/cond/pjit bodies) recursed with independent
numbering, consts reduced to (shape, dtype, content hash). Two traces
of the same program — in different processes, under different ambient
mesh/explain/config state — produce the same fingerprint; any change to
the traced computation changes it. This is what turns "identical jaxpr,
zero added retraces" from scattered per-test assertions into a
whole-fleet invariant the differ (jaxlint.diff) can prove.

The renderer must be process-stable: no ``id()``, no raw ``repr`` of
objects whose repr embeds addresses (those are scrubbed), no dict/set
iteration-order dependence (params are sorted by key).
"""

from __future__ import annotations

import hashlib
import re
import threading

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def _aval_str(aval) -> str:
    weak = ",w" if getattr(aval, "weak_type", False) else ""
    shape = ",".join(str(int(d)) for d in getattr(aval, "shape", ()))
    return f"{getattr(aval, 'dtype', '?')}[{shape}]{weak}"


def _norm_param(v) -> str:
    """Normalize one equation param to a process-stable string."""
    import numpy as np

    if hasattr(v, "jaxpr") or hasattr(v, "eqns"):  # ClosedJaxpr / Jaxpr
        closed = v if hasattr(v, "jaxpr") else None
        if closed is not None:
            return "jaxpr{" + canonical_text(closed) + "}"
        return "jaxpr{" + _canon_open(v) + "}"
    if isinstance(v, (tuple, list)):
        return "[" + ",".join(_norm_param(x) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{k}:{_norm_param(x)}" for k, x in sorted(v.items())
        ) + "}"
    if isinstance(v, np.dtype):
        return str(v)
    if hasattr(v, "shape") and hasattr(v, "dtype") and hasattr(
        v, "__array__"
    ):
        arr = np.ascontiguousarray(np.asarray(v))
        digest = hashlib.sha256(arr.tobytes()).hexdigest()[:12]
        return f"arr({_ADDR_RE.sub('', str(arr.dtype))}" \
               f"[{','.join(map(str, arr.shape))}],{digest})"
    if callable(v):
        return f"fn:{getattr(v, '__name__', type(v).__name__)}"
    return _ADDR_RE.sub("0xADDR", repr(v))


def _var_namer():
    names: dict = {}

    def name_of(v):
        import jax

        if isinstance(v, jax.core.Literal):
            return f"lit({_norm_param(v.val)}:{_aval_str(v.aval)})"
        if v not in names:
            names[v] = f"v{len(names)}"
        return names[v]

    return name_of


def _canon_open(jaxpr) -> str:
    """Canonical text of an OPEN jaxpr (no consts attached)."""
    name_of = _var_namer()
    lines = []
    lines.append(
        "in=" + ",".join(f"{name_of(v)}:{_aval_str(v.aval)}"
                         for v in jaxpr.invars)
    )
    if jaxpr.constvars:
        lines.append(
            "constvars=" + ",".join(
                f"{name_of(v)}:{_aval_str(v.aval)}"
                for v in jaxpr.constvars
            )
        )
    for eqn in jaxpr.eqns:
        params = ",".join(
            f"{k}={_norm_param(v)}" for k, v in sorted(eqn.params.items())
        )
        ins = ",".join(name_of(v) for v in eqn.invars)
        outs = ",".join(
            f"{name_of(v)}:{_aval_str(v.aval)}" for v in eqn.outvars
        )
        lines.append(f"{eqn.primitive.name}({ins})->({outs})|{params}")
    lines.append("out=" + ",".join(name_of(v) for v in jaxpr.outvars))
    return "\n".join(lines)


def canonical_text(closed) -> str:
    """Canonical rendering of a ClosedJaxpr, consts included by value."""
    consts = ",".join(_norm_param(c) for c in closed.consts)
    body = _canon_open(closed.jaxpr)
    return (f"consts=[{consts}]\n" if consts else "") + body


def fingerprint(closed) -> str:
    """16-hex-char canonical hash of a ClosedJaxpr."""
    return hashlib.sha256(
        canonical_text(closed).encode("utf-8")
    ).hexdigest()[:16]


# -- per-kernel fingerprint cache --------------------------------------------
#
# (kernel name, spec sig) -> fingerprint. Re-tracing is cheap (~ms at
# production shapes, no compile) but not free; the bench detail blocks
# and /v1/agent/trace read through this cache so repeated surfacing
# costs one dict lookup.

_fp_lock = threading.Lock()
_fp_cache: dict[tuple[str, str], str] = {}


def fingerprint_for(entry, sig: str) -> str:
    """Fingerprint of one recorded config of one kernel (cached)."""
    key = (entry.name, sig)
    with _fp_lock:
        cached = _fp_cache.get(key)
    if cached is not None:
        return cached
    from . import retracer

    fp = fingerprint(retracer.retrace(entry, entry.specs[sig]))
    with _fp_lock:
        _fp_cache[key] = fp
    return fp


def reset_fingerprint_cache() -> None:
    with _fp_lock:
        _fp_cache.clear()


def fingerprint_table(registry=None, production_only: bool = True) -> dict:
    """{kernel name: {config label: fingerprint}} for every registered
    kernel with at least one recorded spec. The bench ``detail`` blocks
    and the /v1/agent/trace kernel profiles embed this so cross-run
    jaxpr drift is diffable from recorded artifacts."""
    from ...utils import backend
    from . import retracer

    if registry is None:
        registry = backend.kernel_registry()
    reg = (
        retracer.production_kernels(registry)
        if production_only
        else registry
    )
    out: dict = {}
    for name, entry in sorted(reg.items()):
        configs = {}
        for sig in entry.specs:
            label = retracer.spec_label(entry, sig)
            try:
                configs[label] = fingerprint_for(entry, sig)
            except Exception as e:  # noqa: BLE001 — surfaced, not hidden
                configs[label] = f"error:{type(e).__name__}"
        if configs:
            out[entry.short] = configs
    return out
