"""Registry -> ClosedJaxpr: re-trace a registered kernel abstractly.

``utils.backend.traced_jit`` records, per kernel, the original un-jitted
body plus the abstract call specs seen at trace time (dynamic args as
(shape, dtype, weak_type) avals, static args as Python values). This
module turns one such spec back into a ``jax.make_jaxpr`` call: static
values are baked in exactly as ``jax.jit(static_argnames=...)`` would
bake them, dynamic slots become ``ShapeDtypeStruct`` avals, and the
result is the SAME jaxpr the production dispatch traced — without ever
materializing data or touching a device.
"""

from __future__ import annotations

from ...utils import backend

#: kernels whose fully-qualified name starts with one of these prefixes
#: are "the production fleet" — test-fixture kernels registered by a
#: pytest process are excluded from whole-fleet checks by default.
PRODUCTION_PREFIXES = ("nomad_tpu.",)


class UnretraceableSpec(ValueError):
    """A recorded spec contains an argument the analyzer cannot rebuild
    abstractly (an opaque Python object passed into a kernel)."""


def import_fleet() -> dict:
    """Import every module that defines production ``traced_jit``
    kernels (decoration registers them), then return the registry."""
    from ...device import cp, preempt, score  # noqa: F401
    from ...scheduler import hetero  # noqa: F401

    return backend.kernel_registry()


def production_kernels(registry=None) -> dict:
    reg = registry if registry is not None else backend.kernel_registry()
    return {
        name: entry
        for name, entry in sorted(reg.items())
        if name.startswith(PRODUCTION_PREFIXES)
    }


def _build_slot(spec_entry, dynamic_slots):
    """("aval", ...) -> placeholder index appended to dynamic_slots;
    ("static", v) -> the baked value."""
    kind = spec_entry[0]
    if kind == "static":
        return spec_entry[1]
    if kind == "aval":
        import jax
        import numpy as np

        _, shape, dtype, weak = spec_entry
        aval = jax.ShapeDtypeStruct(
            tuple(shape), np.dtype(dtype), weak_type=bool(weak)
        )
        dynamic_slots.append(aval)
        return _Dyn(len(dynamic_slots) - 1)
    raise UnretraceableSpec(
        f"opaque argument of type {spec_entry[1]!r} — the kernel was "
        "called with a Python object the analyzer cannot abstract"
    )


class _Dyn:
    __slots__ = ("idx",)

    def __init__(self, idx):
        self.idx = idx


def retrace(entry, spec=None):
    """Re-trace ``entry`` (a backend.KernelEntry) from ``spec`` (default:
    its newest recorded spec). Returns a ``ClosedJaxpr``."""
    import jax

    if spec is None:
        spec = entry.last_spec()
    if spec is None:
        raise UnretraceableSpec(
            f"kernel {entry.name} has no recorded call spec — run the "
            "exercise workload (jaxlint.exercise) or a bench first"
        )
    dynamic_slots: list = []
    pos_template = [_build_slot(s, dynamic_slots) for s in spec["args"]]
    kw_template = {
        k: _build_slot(s, dynamic_slots) for k, s in spec["kwargs"].items()
    }

    def _call(*dyn):
        pos = [dyn[t.idx] if isinstance(t, _Dyn) else t
               for t in pos_template]
        kw = {k: dyn[t.idx] if isinstance(t, _Dyn) else t
              for k, t in kw_template.items()}
        return entry.fn(*pos, **kw)

    return jax.make_jaxpr(_call)(*dynamic_slots)


def spec_label(entry, sig: str) -> str:
    """Human label for one recorded spec: the static/Python-valued args
    that distinguish configs of the same kernel (dynamic shapes are in
    the sig itself, which can be long — statics are what operators
    diff). Omitted trailing params with non-tensor defaults count too:
    ``throughputs=None`` left at its default routes a Python gate and is
    a different jit cache entry than a supplied array."""
    import inspect

    spec = entry.specs.get(sig)
    if spec is None:
        return sig[:64]
    try:
        params = list(inspect.signature(entry.fn).parameters.values())
    except (TypeError, ValueError):
        params = None

    def pname(i):
        if params is not None and i < len(params) and params[i].kind in (
            inspect.Parameter.POSITIONAL_ONLY,
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
        ):
            return params[i].name
        return f"arg{i}"

    statics = []
    for i, s in enumerate(spec["args"]):
        if s[0] == "static":
            statics.append(f"{pname(i)}={s[1]!r}")
    for k, s in spec["kwargs"].items():
        if s[0] == "static":
            statics.append(f"{k}={s[1]!r}")
    if params is not None:
        for p in params[len(spec["args"]):]:
            if (
                p.name in spec["kwargs"]
                or p.default is inspect.Parameter.empty
                or p.kind not in (
                    inspect.Parameter.POSITIONAL_OR_KEYWORD,
                    inspect.Parameter.KEYWORD_ONLY,
                )
            ):
                continue
            statics.append(f"{p.name}={p.default!r}")
    statics.sort()
    return ", ".join(statics) if statics else "default"
