"""Runtime lock-graph race detector (the lockdep analog).

Env-gated via ``NOMAD_TPU_RACECHECK=1``: ``install()`` replaces
``threading.Lock``/``RLock``/``Condition`` with instrumented wrappers for
locks *created from nomad_tpu or test code* (stdlib internals keep real
locks — the creation-site filter keeps the blast radius at zero for
logging/queue/concurrent.futures machinery). Each wrapper records, per
thread, the stack of held locks; acquiring B while holding A adds the
edge A→B to a global lock graph keyed by creation site (two instances
born on the same line are the same graph node, exactly how lockdep
classes locks). A cycle in that graph is a deadlock that merely hasn't
fired yet.

Guarded fields: ``guarded_by("_lock")`` is a class-level descriptor that,
while a detector is installed, verifies the instance's named lock is held
by the accessing thread and records a violation otherwise — the runtime
twin of the static NTA005 rule.

Usage (tests/test_concurrency_invariants.py, broker/cluster tests):

    with race.racecheck() as graph:
        ...construct brokers/stores/workers and hammer them...
    # racecheck() raises RaceError on cycles or guarded-field violations

or, env-gated for a whole test module, via the conftest fixture.
"""

from __future__ import annotations

import os
import sys
import threading
from contextlib import contextmanager

ENV_VAR = "NOMAD_TPU_RACECHECK"

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition


def enabled() -> bool:
    return os.environ.get(ENV_VAR, "") not in ("", "0")


class RaceError(AssertionError):
    """Lock-order cycle or guarded-field violation."""


class LockGraph:
    """Global acquisition-order graph + guarded-field violation log."""

    def __init__(self):
        # the graph's own lock must be a REAL lock: it is taken inside
        # every instrumented acquire and must never recurse into itself
        self._mu = _REAL_LOCK()
        # (held_site, acquired_site) -> example "thread: held -> acquired"
        self._edges: dict[tuple[str, str], str] = {}
        self._tls = threading.local()
        self._field_violations: list[str] = []

    # -- per-thread held stack ---------------------------------------------
    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def note_acquire(self, lock: "_InstrumentedBase") -> None:
        held = self._held()
        new_edges = []
        for other in held:
            if other is lock or other.nta_name == lock.nta_name:
                continue  # reentrancy / same lock class: not an ordering
            new_edges.append((other.nta_name, lock.nta_name))
        held.append(lock)
        if new_edges:
            tname = threading.current_thread().name
            with self._mu:
                for e in new_edges:
                    self._edges.setdefault(e, f"{tname}: {e[0]} -> {e[1]}")

    def note_release(self, lock: "_InstrumentedBase") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def holds(self, lock: "_InstrumentedBase") -> bool:
        return any(h is lock for h in self._held())

    def held_count(self, lock: "_InstrumentedBase") -> int:
        return sum(1 for h in self._held() if h is lock)

    # -- guarded fields ----------------------------------------------------
    def note_unguarded(self, desc: str) -> None:
        with self._mu:
            self._field_violations.append(desc)

    # -- reporting ---------------------------------------------------------
    def edges(self) -> dict[tuple[str, str], str]:
        with self._mu:
            return dict(self._edges)

    def field_violations(self) -> list[str]:
        with self._mu:
            return list(self._field_violations)

    def cycles(self) -> list[list[str]]:
        """Enumerate simple cycles in the acquired-before graph (each
        reported once, from its lexicographically smallest node)."""
        edges = self.edges()
        adj: dict[str, set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
        cycles: list[list[str]] = []
        seen_cycles: set[tuple[str, ...]] = set()

        def dfs(start: str, node: str, path: list[str]) -> None:
            for nxt in sorted(adj.get(node, ())):
                if nxt == start:
                    # canonicalize: rotate so min node leads
                    cyc = path[:]
                    m = cyc.index(min(cyc))
                    key = tuple(cyc[m:] + cyc[:m])
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        cycles.append(list(key))
                elif nxt > start and nxt not in path:
                    dfs(start, nxt, path + [nxt])

        for start in sorted(adj):
            dfs(start, start, [start])
        return cycles

    def report(self) -> dict:
        return {
            "edges": {f"{a} -> {b}": ex for (a, b), ex in self.edges().items()},
            "cycles": self.cycles(),
            "field_violations": self.field_violations(),
        }

    def assert_clean(self) -> None:
        cycles = self.cycles()
        fields = self.field_violations()
        if cycles or fields:
            lines = []
            for c in cycles:
                lines.append("lock-order cycle: " + " -> ".join(c + [c[0]]))
            lines.extend(fields)
            raise RaceError("; ".join(lines))


class _InstrumentedBase:
    """Shared bookkeeping for Lock/RLock wrappers. Implements the private
    hooks ``threading.Condition`` probes (``_is_owned``, ``_release_save``,
    ``_acquire_restore``) so instrumented locks nest under Conditions."""

    def __init__(self, graph: LockGraph, name: str, inner):
        self._inner = inner
        self.nta_graph = graph
        self.nta_name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self.nta_graph.note_acquire(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        self.nta_graph.note_release(self)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _is_owned(self) -> bool:  # Condition support
        return self.nta_graph.holds(self)

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.nta_name} of {self._inner!r}>"


class _InstrumentedLock(_InstrumentedBase):
    def _release_save(self):  # Condition.wait on a plain Lock
        self.release()

    def _acquire_restore(self, _state) -> None:
        self.acquire()


class _InstrumentedRLock(_InstrumentedBase):
    def locked(self) -> bool:
        # RLock.locked() exists on 3.12+; emulate via ownership otherwise
        try:
            return self._inner.locked()
        except AttributeError:
            return self.nta_graph.held_count(self) > 0

    def _release_save(self):
        # Condition.wait must drop ALL recursive holds
        count = self.nta_graph.held_count(self)
        state = self._inner._release_save()
        for _ in range(count):
            self.nta_graph.note_release(self)
        return (state, count)

    def _acquire_restore(self, state) -> None:
        inner_state, count = state
        self._inner._acquire_restore(inner_state)
        for _ in range(count):
            self.nta_graph.note_acquire(self)


# -- installation -----------------------------------------------------------

_active_graph: LockGraph | None = None
_install_depth = 0


def active_graph() -> LockGraph | None:
    return _active_graph


def _creation_site(depth: int = 2) -> str | None:
    """``file.py:lineno`` of the Lock() call site, or None when the lock
    is born outside nomad_tpu/test code and should stay uninstrumented."""
    frame = sys._getframe(depth)
    fname = frame.f_code.co_filename
    base = os.path.basename(fname)
    if "nomad_tpu" not in fname and not base.startswith("test"):
        return None
    return f"{base}:{frame.f_lineno}"


def _lock_factory():
    site = _creation_site()
    if site is None or _active_graph is None:
        return _REAL_LOCK()
    return _InstrumentedLock(_active_graph, site, _REAL_LOCK())


def _rlock_factory():
    site = _creation_site()
    if site is None or _active_graph is None:
        return _REAL_RLOCK()
    return _InstrumentedRLock(_active_graph, site, _REAL_RLOCK())


def _condition_factory(lock=None):
    # a bare Condition() would build its RLock from inside threading.py
    # (filtered as stdlib); hand it an instrumented one from the real
    # caller's site instead
    if lock is None and _active_graph is not None:
        site = _creation_site()
        if site is not None:
            lock = _InstrumentedRLock(_active_graph, site, _REAL_RLOCK())
    return _REAL_CONDITION(lock)


def install() -> LockGraph:
    """Start a detection window: fresh graph, patched lock factories.
    Locks created before install() keep their real implementation —
    construct the objects under test inside the window."""
    global _active_graph, _install_depth
    _install_depth += 1
    if _active_graph is None:
        _active_graph = LockGraph()
        threading.Lock = _lock_factory
        threading.RLock = _rlock_factory
        threading.Condition = _condition_factory
    return _active_graph


def uninstall() -> None:
    global _active_graph, _install_depth
    _install_depth = max(0, _install_depth - 1)
    if _install_depth == 0:
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        threading.Condition = _REAL_CONDITION
        _active_graph = None


@contextmanager
def racecheck(strict: bool = True):
    """Detection window as a context manager; on exit, raises RaceError
    when strict and the graph saw a cycle or guarded-field violation."""
    graph = install()
    try:
        yield graph
    finally:
        uninstall()
    if strict:
        graph.assert_clean()


# -- guarded fields ----------------------------------------------------------


class guarded_by:
    """Class-level descriptor declaring which lock guards a field::

        class Store:
            watermark = guarded_by("_lock")

    While a detector is installed and the instance's lock is an
    instrumented one, every get/set verifies the current thread holds
    that lock; violations land in the graph's field report instead of
    raising at the access site (the access itself is still performed, so
    production behavior is unchanged)."""

    def __init__(self, lock_attr: str):
        self.lock_attr = lock_attr
        self.name = "<unbound>"

    def __set_name__(self, owner, name: str) -> None:
        self.name = name
        self.slot = f"_guarded_{name}"

    def _check(self, obj, op: str) -> None:
        lock = getattr(obj, self.lock_attr, None)
        if isinstance(lock, _InstrumentedBase):
            graph = lock.nta_graph
            if not graph.holds(lock):
                graph.note_unguarded(
                    f"unguarded {op} of {type(obj).__name__}.{self.name} "
                    f"without holding {self.lock_attr} "
                    f"({lock.nta_name}) on thread "
                    f"{threading.current_thread().name}"
                )

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        self._check(obj, "read")
        return getattr(obj, self.slot, None)

    def __set__(self, obj, value) -> None:
        self._check(obj, "write")
        object.__setattr__(obj, self.slot, value)
