"""AST lint engine: findings, rule registry, baseline ratchet, runner.

The engine is deliberately boring: every rule is an ``ast`` walk over one
file, a finding is a (rule, path, symbol, message) tuple, and the whole
repo is analyzed from scratch on every run (~100 files parses in well
under a second). The interesting part is the *ratchet*: findings are
fingerprinted WITHOUT line numbers, so unrelated edits never churn the
baseline, and a violation only leaves the baseline when the code it
points at is actually fixed (or ``--fix-baseline`` is run).

Inline suppression: a line containing ``# nta: allow`` waives every rule
for findings anchored on that line; ``# nta: allow=NTA001,NTA005`` waives
only the named rules. Use sparingly — the comment is the audit trail.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Optional

_ALLOW_RE = re.compile(r"#\s*nta:\s*allow(?:=([A-Za-z0-9_,]+))?")

# directories under the repo root that the default whole-repo run scans
DEFAULT_SCAN_DIRS = ("nomad_tpu",)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    symbol: str  # enclosing Class.method / function qualname ("" = module)
    message: str

    @property
    def fingerprint(self) -> str:
        """Line-free ratchet key: survives unrelated edits to the file."""
        return f"{self.rule}:{self.path}:{self.symbol}:{self.message}"

    def render(self) -> str:
        where = f" (in {self.symbol})" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{where}"


class Rule:
    """Base class for repo-specific rules. Subclasses set ``id`` and
    ``title``, implement ``applies_to`` (path scoping) and ``check``."""

    id: str = ""
    title: str = ""

    def applies_to(self, relpath: str) -> bool:
        raise NotImplementedError

    def check(self, tree: ast.AST, source: str, relpath: str) -> list[Finding]:
        raise NotImplementedError


class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the enclosing class/function qualname so
    rules can anchor findings on a stable symbol instead of a line."""

    def __init__(self, relpath: str):
        self.relpath = relpath
        self._scope: list[str] = []
        self.findings: list[Finding] = []

    def qualname(self) -> str:
        return ".".join(self._scope)

    def _push(self, name: str, node: ast.AST) -> None:
        self._scope.append(name)
        self.generic_visit(node)
        self._scope.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._push(node.name, node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._push(node.name, node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._push(node.name, node)

    def add(self, rule_id: str, node: ast.AST, message: str) -> None:
        self.findings.append(
            Finding(
                rule=rule_id,
                path=self.relpath,
                line=getattr(node, "lineno", 0),
                symbol=self.qualname(),
                message=message,
            )
        )


def dotted_name(node: ast.AST) -> Optional[str]:
    """'time.time' for Attribute(Name('time'), 'time'); None for dynamic
    bases (calls, subscripts) the rules can't resolve statically."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def suppressed_lines(source: str) -> dict[int, Optional[set[str]]]:
    """line number -> None (allow all rules) or set of allowed rule ids."""
    out: dict[int, Optional[set[str]]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _ALLOW_RE.search(text)
        if not m:
            continue
        if m.group(1):
            out[i] = {r.strip().upper() for r in m.group(1).split(",")}
        else:
            out[i] = None
    return out


def _apply_suppressions(
    findings: list[Finding], source: str
) -> list[Finding]:
    allow = suppressed_lines(source)
    if not allow:
        return findings
    kept = []
    for f in findings:
        rules = allow.get(f.line, "missing")
        if rules == "missing":
            kept.append(f)
        elif rules is not None and f.rule not in rules:
            kept.append(f)
    return kept


# -- runner ----------------------------------------------------------------


def all_rules() -> list[Rule]:
    from .rules import REGISTRY

    return [cls() for cls in REGISTRY]


def check_source(
    source: str, relpath: str, rules: Optional[Iterable[Rule]] = None
) -> list[Finding]:
    """Lint one in-memory source blob as if it lived at ``relpath``
    (repo-relative). This is the fixture seam the rule tests use."""
    relpath = relpath.replace("\\", "/")
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [
            Finding(
                rule="NTA000",
                path=relpath,
                line=e.lineno or 0,
                symbol="",
                message=f"syntax error: {e.msg}",
            )
        ]
    findings: list[Finding] = []
    for rule in rules if rules is not None else all_rules():
        if rule.applies_to(relpath):
            findings.extend(rule.check(tree, source, relpath))
    findings = _apply_suppressions(findings, source)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


def iter_python_files(root: Path) -> list[Path]:
    files: list[Path] = []
    for d in DEFAULT_SCAN_DIRS:
        base = root / d
        if base.is_dir():
            files.extend(base.rglob("*.py"))
    return sorted(files)


def run_lint(
    root: Path,
    paths: Optional[Iterable[Path]] = None,
    rules: Optional[Iterable[Rule]] = None,
) -> list[Finding]:
    root = Path(root).resolve()
    rules = list(rules) if rules is not None else all_rules()
    targets = (
        [Path(p).resolve() for p in paths]
        if paths
        else iter_python_files(root)
    )
    findings: list[Finding] = []
    for path in targets:
        try:
            relpath = path.relative_to(root).as_posix()
        except ValueError:
            relpath = path.as_posix()
        findings.extend(
            check_source(path.read_text(encoding="utf-8"), relpath, rules)
        )
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


# -- baseline ratchet -------------------------------------------------------


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def repo_root() -> Path:
    """Directory containing the ``nomad_tpu`` package."""
    return Path(__file__).resolve().parents[2]


def load_baseline(path: Path) -> set[str]:
    path = Path(path)
    if not path.exists():
        return set()
    data = json.loads(path.read_text(encoding="utf-8"))
    return {e["fingerprint"] for e in data.get("entries", [])}


def write_baseline(findings: list[Finding], path: Path) -> None:
    entries = sorted(
        (
            {
                "fingerprint": f.fingerprint,
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "message": f.message,
            }
            for f in findings
        ),
        key=lambda e: e["fingerprint"],
    )
    # dedupe identical fingerprints (e.g. the same message on two lines):
    # the ratchet tracks presence, not multiplicity
    seen: set[str] = set()
    unique = []
    for e in entries:
        if e["fingerprint"] not in seen:
            seen.add(e["fingerprint"])
            unique.append(e)
    Path(path).write_text(
        json.dumps({"version": 1, "entries": unique}, indent=2) + "\n",
        encoding="utf-8",
    )


def diff_against_baseline(
    findings: list[Finding], baseline: set[str]
) -> tuple[list[Finding], set[str]]:
    """Returns (new findings not in baseline, baseline fingerprints that
    no longer fire — i.e. fixed and eligible for --fix-baseline)."""
    fps = {f.fingerprint for f in findings}
    new = [f for f in findings if f.fingerprint not in baseline]
    fixed = baseline - fps
    return new, fixed
