"""CLI: ``python -m nomad_tpu.analysis``.

Default action: lint the repo, diff against the checked-in baseline,
exit 1 on any NEW finding (pre-existing baselined findings are reported
as ratcheted, not blocking). ``--fix-baseline`` regenerates the baseline
deterministically (sorted entries, path-relative, line-number-free
fingerprints) — run it after fixing violations so the ratchet tightens.

    python -m nomad_tpu.analysis                  # lint vs baseline
    python -m nomad_tpu.analysis --json           # machine-readable
    python -m nomad_tpu.analysis --rules NTA003   # subset of rules
    python -m nomad_tpu.analysis --fix-baseline   # regenerate baseline
    python -m nomad_tpu.analysis --retrace-report # jit budget registry
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import lint


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m nomad_tpu.analysis",
        description="repo-specific static analysis (NTA001-NTA009)",
    )
    p.add_argument(
        "paths", nargs="*",
        help="specific .py files to lint (default: whole nomad_tpu tree)",
    )
    p.add_argument(
        "--root", type=Path, default=None,
        help="repo root (default: the tree containing this package)",
    )
    p.add_argument(
        "--baseline", type=Path, default=None,
        help="baseline file (default: nomad_tpu/analysis/baseline.json)",
    )
    p.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--fix-baseline", action="store_true",
        help="regenerate the baseline from current findings and exit 0",
    )
    p.add_argument("--json", action="store_true", help="JSON output")
    p.add_argument(
        "--retrace-report", action="store_true",
        help="print the jit trace-count/budget registry and exit "
        "(imports the device kernels)",
    )
    args = p.parse_args(argv)

    if args.retrace_report:
        from . import retrace
        from ..device import preempt, score  # noqa: F401 — registers kernels

        print(json.dumps(retrace.report(), indent=2))
        return 0

    root = (args.root or lint.repo_root()).resolve()
    rules = None
    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        rules = [r for r in lint.all_rules() if r.id in wanted]
        missing = wanted - {r.id for r in rules}
        if missing:
            print(f"unknown rules: {', '.join(sorted(missing))}",
                  file=sys.stderr)
            return 2

    findings = lint.run_lint(root, paths=args.paths or None, rules=rules)

    baseline_path = args.baseline or lint.default_baseline_path()
    if args.fix_baseline:
        lint.write_baseline(findings, baseline_path)
        print(
            f"baseline regenerated: {len(findings)} finding(s) -> "
            f"{baseline_path}"
        )
        return 0

    baseline = lint.load_baseline(baseline_path)
    new, fixed = lint.diff_against_baseline(findings, baseline)
    ratcheted = len(findings) - len(new)

    if args.json:
        print(json.dumps({
            "new": [f.__dict__ | {"fingerprint": f.fingerprint} for f in new],
            "ratcheted": ratcheted,
            "fixed": sorted(fixed),
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if fixed:
            print(
                f"note: {len(fixed)} baselined finding(s) no longer fire — "
                f"run --fix-baseline to tighten the ratchet"
            )
        print(
            f"{len(new)} new finding(s), {ratcheted} ratcheted "
            f"(baselined), {len(fixed)} fixed"
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
