"""CLI: ``python -m nomad_tpu.analysis``.

Default action: BOTH analyses in one invocation — the NTA source lint
(AST over the repo tree) and the JXL jaxpr lint (re-traced device-kernel
fleet) — each diffed against its own checked-in baseline, with the
combined exit code (1 if either surfaced a new finding). Pre-existing
baselined findings are reported as ratcheted, not blocking.
``--fix-baseline`` regenerates BOTH baselines deterministically (sorted
entries, path-relative, line-number-free fingerprints) — run it after
fixing violations so the ratchets tighten.

    python -m nomad_tpu.analysis                  # source + kernels
    python -m nomad_tpu.analysis --source-only    # AST rules only (fast)
    python -m nomad_tpu.analysis --kernels-only   # jaxpr rules only
    python -m nomad_tpu.analysis --json           # machine-readable
    python -m nomad_tpu.analysis --rules NTA003   # subset (implies source)
    python -m nomad_tpu.analysis --fix-baseline   # regenerate baseline(s)
    python -m nomad_tpu.analysis --retrace-report # jit budget registry
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import lint


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m nomad_tpu.analysis",
        description="repo-specific static analysis: NTA source rules + "
        "JXL traced-kernel rules",
    )
    p.add_argument(
        "paths", nargs="*",
        help="specific .py files to lint (default: whole nomad_tpu tree)",
    )
    p.add_argument(
        "--root", type=Path, default=None,
        help="repo root (default: the tree containing this package)",
    )
    p.add_argument(
        "--baseline", type=Path, default=None,
        help="source baseline file (default: "
        "nomad_tpu/analysis/baseline.json)",
    )
    p.add_argument(
        "--kernel-baseline", type=Path, default=None,
        help="jaxpr baseline file (default: "
        "nomad_tpu/analysis/jaxlint/baseline.json)",
    )
    p.add_argument(
        "--rules", default=None,
        help="comma-separated source rule ids to run (default: all; "
        "implies --source-only)",
    )
    p.add_argument(
        "--fix-baseline", action="store_true",
        help="regenerate the baseline(s) from current findings, exit 0",
    )
    p.add_argument("--json", action="store_true", help="JSON output")
    only = p.add_mutually_exclusive_group()
    only.add_argument(
        "--source-only", action="store_true",
        help="run only the NTA source lint (no jax import, no tracing)",
    )
    only.add_argument(
        "--kernels-only", action="store_true",
        help="run only the JXL jaxpr lint over the traced kernel fleet",
    )
    p.add_argument(
        "--retrace-report", action="store_true",
        help="print the jit trace-count/budget registry and exit "
        "(imports the device kernels)",
    )
    args = p.parse_args(argv)

    if args.retrace_report:
        from . import retrace
        from ..device import preempt, score  # noqa: F401 — registers kernels

        print(json.dumps(retrace.report(), indent=2))
        return 0

    run_source = not args.kernels_only
    run_kernels = not args.source_only and not args.rules and not args.paths

    out = {"source": None, "kernels": None}
    exit_code = 0

    if run_source:
        root = (args.root or lint.repo_root()).resolve()
        rules = None
        if args.rules:
            wanted = {r.strip().upper() for r in args.rules.split(",")}
            rules = [r for r in lint.all_rules() if r.id in wanted]
            missing = wanted - {r.id for r in rules}
            if missing:
                print(f"unknown rules: {', '.join(sorted(missing))}",
                      file=sys.stderr)
                return 2
        findings = lint.run_lint(
            root, paths=args.paths or None, rules=rules
        )
        baseline_path = args.baseline or lint.default_baseline_path()
        if args.fix_baseline:
            lint.write_baseline(findings, baseline_path)
            out["source"] = {"regenerated": len(findings)}
        else:
            baseline = lint.load_baseline(baseline_path)
            new, fixed = lint.diff_against_baseline(findings, baseline)
            out["source"] = {
                "new": new,
                "ratcheted": len(findings) - len(new),
                "fixed": sorted(fixed),
            }
            exit_code |= 1 if new else 0

    if run_kernels:
        from .jaxlint import engine

        kb = args.kernel_baseline or engine.default_baseline_path()
        code, new, fixed, reports = engine.run_jaxlint(
            baseline_path=kb, fix_baseline=args.fix_baseline
        )
        out["kernels"] = {
            "new": new,
            "fixed": sorted(fixed),
            "analyzed": len(reports),
            "configs": sum(len(r["configs"]) for r in reports.values()),
        }
        exit_code |= code

    if args.json:
        def enc(section):
            if section is None or "new" not in section:
                return section
            return section | {"new": [
                f.__dict__ | {"fingerprint": f.fingerprint}
                for f in section["new"]
            ]}

        print(json.dumps(
            {k: enc(v) for k, v in out.items()}, indent=2
        ))
        return exit_code

    if args.fix_baseline:
        if out["source"] is not None:
            print(
                f"source baseline regenerated: "
                f"{out['source']['regenerated']} finding(s)"
            )
        if out["kernels"] is not None:
            print(
                f"kernel baseline regenerated: "
                f"{len(out['kernels']['new'])} new finding(s) absorbed"
            )
        return 0

    for section, label in ((out["source"], "source"),
                           (out["kernels"], "kernels")):
        if section is None:
            continue
        for f in section["new"]:
            print(f.render())
        if section["fixed"]:
            print(
                f"note: {len(section['fixed'])} baselined {label} "
                "finding(s) no longer fire — run --fix-baseline to "
                "tighten the ratchet"
            )
    src = out["source"]
    if src is not None:
        print(
            f"source: {len(src['new'])} new finding(s), "
            f"{src['ratcheted']} ratcheted (baselined), "
            f"{len(src['fixed'])} fixed"
        )
    ker = out["kernels"]
    if ker is not None:
        print(
            f"kernels: {len(ker['new'])} new finding(s) across "
            f"{ker['analyzed']} kernel(s) / {ker['configs']} config(s), "
            f"{len(ker['fixed'])} fixed"
        )
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
