"""NTA012 — external intake routes through the admission controller.

Overload protection (server/admission.py) only works if every seam
where outside traffic enters the eval pipeline consults the controller
*before* committing work. The architecture concentrates those seams in
two places: the HTTP/RPC handlers under ``api/`` (which turn requests
into evals via ``apply_eval_create`` / ``eval_broker.enqueue``) and the
broker package itself (whose public ``enqueue`` paths funnel through
``_enqueue_locked``, the one site that calls ``gate_enqueue``). A new
handler that injects evals without an admission check compiles, runs,
and passes every functional test — then under a 2× overload spike it
becomes the unprotected side door that sinks the high-priority SLO the
controller exists to defend.

Flagged:

- in ``api/`` modules: a function that calls ``apply_eval_create(...)``
  or ``eval_broker.enqueue*(...)`` without also making an admission-
  controller call (any dotted call through an ``admission`` attribute,
  e.g. ``self.server.admission.check_intake(...)``) somewhere in the
  same function — the gate and the injection must be visibly paired;
- in ``api/`` and ``broker/`` modules other than ``eval_broker.py``:
  any reference to ``_enqueue_locked`` or the broker's ``_ready``
  queues — internals that bypass the gated public enqueue entirely.

Scope: ``nomad_tpu/api/`` and ``nomad_tpu/broker/``. The broker's own
``eval_broker.py`` is exempt from the internals check (it IS the
implementation); server-side intake (``register_job`` / ``scale_job``)
gates inside ``server.py`` where NTA012's call-pairing heuristic would
be noise, so it is covered by tests rather than lint.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_API_PREFIX = "nomad_tpu/api/"
_BROKER_PREFIX = "nomad_tpu/broker/"
_BROKER_IMPL = "nomad_tpu/broker/eval_broker.py"

# calls that inject work into the eval pipeline from an api/ handler
_INJECTORS = ("apply_eval_create",)
_ENQUEUE_PREFIX = "eval_broker.enqueue"

# broker internals that bypass the gated public enqueue
_INTERNALS = ("_enqueue_locked", "_ready")


def _is_admission_call(name: str) -> bool:
    """True for any dotted call routed through an ``admission``
    attribute: ``self.server.admission.check_intake`` etc."""
    parts = name.split(".")
    return "admission" in parts[:-1]


class _ApiVisitor(ScopedVisitor):
    """Per-function pairing check: collect injection calls and admission
    calls per enclosing function, emit findings for unpaired injectors
    when the function scope closes."""

    def __init__(self, relpath: str):
        super().__init__(relpath)
        # stack parallel to _scope: (injector call nodes, gated?) per fn
        self._fn_stack: list[list] = []

    def _visit_fn(self, node) -> None:
        self._fn_stack.append([[], False])
        # emit before the scope pops so findings anchor on the handler's
        # qualname, not its enclosing class
        self._scope.append(node.name)
        self.generic_visit(node)
        injectors, gated = self._fn_stack.pop()
        if not gated:
            for call_node, name in injectors:
                self.add(
                    "NTA012",
                    call_node,
                    f"{name}(...) without an admission-controller check "
                    "in the same handler: external intake must pair the "
                    "injection with admission.check_intake/gate so "
                    "overload shedding covers every entry seam",
                )
        self._scope.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func) or ""
        if self._fn_stack:
            leaf = name.rsplit(".", 1)[-1]
            if leaf in _INJECTORS or _ENQUEUE_PREFIX in name:
                self._fn_stack[-1][0].append((node, name))
            elif _is_admission_call(name):
                self._fn_stack[-1][1] = True
        self.generic_visit(node)


class _InternalsVisitor(ScopedVisitor):
    def _flag(self, node: ast.AST, attr: str) -> None:
        self.add(
            "NTA012",
            node,
            f"reference to broker internal '{attr}' outside "
            "eval_broker.py: inject evals through the public enqueue "
            "API so the admission gate inside _enqueue_locked applies",
        )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in _INTERNALS:
            self._flag(node, node.attr)
        self.generic_visit(node)


class AdmissionGateDiscipline(Rule):
    id = "NTA012"
    title = "external intake routes through the admission controller"

    def applies_to(self, relpath: str) -> bool:
        if relpath == _BROKER_IMPL:
            return False
        return relpath.startswith((_API_PREFIX, _BROKER_PREFIX))

    def check(self, tree, source, relpath) -> list[Finding]:
        findings: list[Finding] = []
        if relpath.startswith(_API_PREFIX):
            v = _ApiVisitor(relpath)
            v.visit(tree)
            findings.extend(v.findings)
        iv = _InternalsVisitor(relpath)
        iv.visit(tree)
        findings.extend(iv.findings)
        return findings
