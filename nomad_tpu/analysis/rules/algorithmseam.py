"""NTA013 — scheduler algorithms dispatch through the plugin registry.

The algorithm registry (scheduler/algorithms.py) is the single seam
between "which algorithm did the operator pick" and "which kernel runs":
``make_kernel`` maps the SchedulerConfiguration string to a placement
kernel and ``score_group`` routes dense-matrix scoring. A scheduler or
server module that constructs ``PlacementKernel(...)`` /
``HeteroPlacementKernel(...)`` directly, or calls
``score_matrix_kernel(...)`` itself, silently pins one algorithm: the
operator flips ``scheduler_algorithm`` to ``hetero-maxmin`` and that
code path keeps binpacking — no error, no test failure, just a policy
that never engages. It also forks validation: the API's "is this name
registered" check stops covering what actually runs.

Flagged: any call whose dotted leaf is ``PlacementKernel``,
``HeteroPlacementKernel``, or ``score_matrix_kernel`` inside
``nomad_tpu/scheduler/`` or ``nomad_tpu/server/``.

Exempt: ``scheduler/algorithms.py`` (the registry IS the dispatcher),
``scheduler/hetero.py``, and ``scheduler/cp.py`` (their kernels
delegate to the base kernel internally, and cp.py's A/B harness
benchmarks against it). The device package itself (``nomad_tpu/device/``) is out
of scope — it defines the kernels and pins them against host oracles
(device/parity.py); the rule polices *dispatch*, not implementation.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_SCOPES = ("nomad_tpu/scheduler/", "nomad_tpu/server/")
_EXEMPT = (
    "nomad_tpu/scheduler/algorithms.py",
    "nomad_tpu/scheduler/hetero.py",
    "nomad_tpu/scheduler/cp.py",
)

_DISPATCH_LEAVES = (
    "PlacementKernel",
    "HeteroPlacementKernel",
    "score_matrix_kernel",
)


class _DispatchVisitor(ScopedVisitor):
    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in _DISPATCH_LEAVES:
            self.add(
                "NTA013",
                node,
                f"direct kernel dispatch {leaf}(...): route through "
                "scheduler/algorithms.py (make_kernel/score_group) so the "
                "configured scheduler_algorithm actually selects the "
                "kernel",
            )
        self.generic_visit(node)


class AlgorithmSeamDiscipline(Rule):
    id = "NTA013"
    title = "scheduler algorithms dispatch through the plugin registry"

    def applies_to(self, relpath: str) -> bool:
        if relpath in _EXEMPT:
            return False
        return relpath.startswith(_SCOPES)

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _DispatchVisitor(relpath)
        v.visit(tree)
        return v.findings
