"""NTA005 — class-level lock discipline.

If a class guards ``self.x`` with ``with self._lock:`` in one method,
then a lock-free ``self.x`` read or write in *another* method of the same
class is (at best) a benign race waiting for a refactor to make it
malign. The threaded commit path lives on exactly this invariant: the
worker's stats, the shared overlay's counters, and the store's watermark
are all guarded fields.

Analysis, per class:
1. lock attributes: ``self.X = threading.Lock()/RLock()/Condition()``
   (dotted or bare-imported) anywhere in the class;
2. guarded fields: every ``self.Y`` *written* inside a ``with self.X:``
   block (plain stores, aug-assigns, and stores through a subscript like
   ``self.stats[k] += 1`` all count);
3. violations: any access (read or write) to a guarded field outside a
   ``with self.X:`` block, in any method other than ``__init__`` /
   ``__new__`` (pre-publication construction is single-threaded by
   definition).

Methods whose name ends in ``_locked`` are exempt — that suffix is the
documented convention for "caller holds the lock".

Scope: ``nomad_tpu/server/``, ``nomad_tpu/broker/``, ``nomad_tpu/state/``,
and ``nomad_tpu/utils/metrics.py``.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..lint import Finding, Rule, dotted_name

_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _find_lock_attrs(cls: ast.ClassDef) -> set[str]:
    locks: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fname = dotted_name(node.value.func)
            if fname in _LOCK_FACTORIES:
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr:
                        locks.add(attr)
    return locks


class _Access:
    __slots__ = ("field", "method", "line", "write", "locks")

    def __init__(self, field, method, line, write, locks):
        self.field = field
        self.method = method
        self.line = line
        self.write = write
        self.locks = locks  # frozenset of lock attrs held at the access


class _MethodScanner(ast.NodeVisitor):
    def __init__(self, method: str, lock_attrs: set[str]):
        self.method = method
        self.lock_attrs = lock_attrs
        self.held: list[str] = []
        self.accesses: list[_Access] = []

    def _record(self, field: str, node: ast.AST, write: bool) -> None:
        self.accesses.append(
            _Access(
                field, self.method, getattr(node, "lineno", 0), write,
                frozenset(self.held),
            )
        )

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr and attr in self.lock_attrs:
                acquired.append(attr)
        self.held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        if acquired:
            del self.held[len(self.held) - len(acquired):]

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr and attr not in self.lock_attrs:
            self._record(attr, node, isinstance(node.ctx, ast.Store))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # self.stats[k] = v / self.obj.field = v: a store through a chain
        # is a WRITE to the self attribute at its base
        for t in node.targets:
            self._mark_chain_write(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._mark_chain_write(node.target)
        self.generic_visit(node)

    def _mark_chain_write(self, target: ast.AST) -> None:
        node = target
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            parent = node.value
            attr = _self_attr(parent) if node is not target else None
            if attr and attr not in self.lock_attrs:
                self._record(attr, parent, True)
                return
            node = parent


class LockDiscipline(Rule):
    id = "NTA005"
    title = "fields written under a lock must never be accessed lock-free"

    def applies_to(self, relpath: str) -> bool:
        return (
            relpath.startswith("nomad_tpu/server/")
            or relpath.startswith("nomad_tpu/broker/")
            or relpath.startswith("nomad_tpu/state/")
            or relpath == "nomad_tpu/utils/metrics.py"
        )

    def check(self, tree, source, relpath) -> list[Finding]:
        findings: list[Finding] = []
        for cls in [
            n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
        ]:
            lock_attrs = _find_lock_attrs(cls)
            if not lock_attrs:
                continue
            accesses: list[_Access] = []
            for item in cls.body:
                if not isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if item.name in ("__init__", "__new__"):
                    continue
                if item.name.endswith("_locked"):
                    continue  # convention: caller holds the lock
                scanner = _MethodScanner(item.name, lock_attrs)
                for stmt in item.body:
                    scanner.visit(stmt)
                accesses.extend(scanner.accesses)

            # guarded = written under at least one lock somewhere
            guarded: dict[str, str] = {}
            for a in accesses:
                if a.write and a.locks:
                    guarded.setdefault(a.field, sorted(a.locks)[0])

            seen: set[tuple[str, str]] = set()
            for a in accesses:
                lock = guarded.get(a.field)
                if lock is None or a.locks:
                    continue
                key = (a.method, a.field)
                if key in seen:
                    continue
                seen.add(key)
                kind = "written" if a.write else "read"
                findings.append(
                    Finding(
                        rule="NTA005",
                        path=relpath,
                        line=a.line,
                        symbol=f"{cls.name}.{a.method}",
                        message=(
                            f"field '{a.field}' is guarded by "
                            f"'self.{lock}' elsewhere in {cls.name} but "
                            f"{kind} lock-free here"
                        ),
                    )
                )
        return findings
