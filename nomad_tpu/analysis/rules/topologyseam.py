"""NTA020 — topology/gang pricing flows only through the cp-gang seam.

The gang kernel (device/cp.py ``cp_gang_place_kernel`` and its host
oracle ``oracle_cp_gang_place``) carries invariants that live OUTSIDE
the kernel: ``scheduler/cp.py`` is where topology id columns flatten
into one-hot level matrices (``build_gang_inputs``), where incomplete
gangs release atomically (``release_incomplete_gangs`` applied to RAW
kernel outputs, after parity), and where the ``nomad.cp.gang_*``
conservation counters are recorded. A scheduler or server module that
calls the gang kernel directly — or re-derives topology adjacency from
``topology_columns``/``topo_onehot`` for its own pricing — bypasses
all of that: gangs can stripe partial placements with no release path,
and two call sites can disagree on what "same rack" means (the one-hot
zeroes the coordinate-less column 0; an ad-hoc ``==`` comparison over
raw ids does not).

Flagged: any call whose dotted leaf is ``cp_gang_place_kernel``,
``oracle_cp_gang_place``, ``release_incomplete_gangs``,
``CpGangPlacementKernel``, ``build_gang_inputs``, ``topo_onehot``, or
``topology_columns`` inside ``nomad_tpu/scheduler/`` or
``nomad_tpu/server/``.

Exempt: ``scheduler/algorithms.py`` (the registry constructs the
kernel wrapper) and ``scheduler/cp.py`` (the seam itself — gang input
assembly, oracle cross-checks, atomic release, and the gang A/B
harness live there). ``nomad_tpu/device/`` is out of scope, as for
NTA016: the rule polices dispatch, not implementation or parity
pinning.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_SCOPES = ("nomad_tpu/scheduler/", "nomad_tpu/server/")
_EXEMPT = (
    "nomad_tpu/scheduler/algorithms.py",
    "nomad_tpu/scheduler/cp.py",
)

_TOPOLOGY_LEAVES = (
    "cp_gang_place_kernel",
    "oracle_cp_gang_place",
    "release_incomplete_gangs",
    "CpGangPlacementKernel",
    "build_gang_inputs",
    "topo_onehot",
    "topology_columns",
)


class _TopologyVisitor(ScopedVisitor):
    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in _TOPOLOGY_LEAVES:
            self.add(
                "NTA020",
                node,
                f"direct topology/gang invocation {leaf}(...): route "
                "through scheduler/algorithms.py (the cp-gang plugin) so "
                "atomic gang release, one-hot topology semantics, and the "
                "nomad.cp.gang_* conservation ledger stay on the path",
            )
        self.generic_visit(node)


class TopologySeamDiscipline(Rule):
    id = "NTA020"
    title = "topology/gang pricing routed only through the cp-gang seam"

    def applies_to(self, relpath: str) -> bool:
        if relpath in _EXEMPT:
            return False
        return relpath.startswith(_SCOPES)

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _TopologyVisitor(relpath)
        v.visit(tree)
        return v.findings
