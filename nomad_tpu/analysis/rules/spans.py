"""NTA006 — eval-lifecycle timing must flow through the span API.

A raw ``metrics.timer(...)`` in an eval-lifecycle module produces a
latency sample that is invisible to the flight recorder: the phase never
appears in a trace tree, so ``nomad-tpu trace <eval>`` and the bench
per-phase breakdown silently under-report where the pipeline spends its
time. ``tracer.span(name, timer="...")`` emits the SAME legacy sample
(tracing on or off) *and* a span, so there is no reason to bypass it in
these modules — one timing call, two surfaces.

Flagged: any call whose dotted name is ``timer`` or ends in ``.timer``
(the ``utils.metrics.Metrics.timer`` context manager). Suppress a
deliberate exception with ``# nta: allow=NTA006``.

Scope: the eval-lifecycle modules instrumented with spans —
``server/worker.py``, ``broker/{eval_broker,plan_queue,plan_apply}.py``,
``scheduler/generic.py``.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_LIFECYCLE_MODULES = (
    "nomad_tpu/server/worker.py",
    "nomad_tpu/broker/eval_broker.py",
    "nomad_tpu/broker/plan_queue.py",
    "nomad_tpu/broker/plan_apply.py",
    "nomad_tpu/scheduler/generic.py",
)


class _Visitor(ScopedVisitor):
    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func) or ""
        if name == "timer" or name.endswith(".timer"):
            self.add(
                "NTA006",
                node,
                f"raw {name}(...) in an eval-lifecycle module: use "
                f"tracer.span(name, timer=...) so the phase shows up in "
                f"traces as well as /v1/metrics",
            )
        self.generic_visit(node)


class SpanCoverage(Rule):
    id = "NTA006"
    title = "eval-lifecycle timing goes through the span API"

    def applies_to(self, relpath: str) -> bool:
        return relpath in _LIFECYCLE_MODULES

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _Visitor(relpath)
        v.visit(tree)
        return v.findings
