"""NTA004 — plans are frozen once submitted to the applier.

The plan applier runs serialized against live state while the worker that
built the plan keeps running; by the time ``apply`` executes, the same
``Plan`` object (and every ``Allocation`` hanging off it) is shared with
the submitting thread, the plan queue, and — on partial commit — the
retry path. One attribute write inside the applier is a data race that
corrupts a snapshot nobody re-validates, silently poisoning every
downstream score matrix. The applier must treat the plan as immutable
input and build its mutations into ``PlanResult`` copies.

Detection: attribute-write analysis over ``broker/plan_apply.py``. A name
is *plan-tainted* when it is a parameter named ``plan`` (or annotated
``Plan``), an alias assigned from one, or a loop variable drawn from a
plan attribute (``for a in plan.node_allocation[...]``). Flagged:
attribute stores/aug-assigns on tainted names, subscript stores into plan
attributes, and mutating method calls (``append``/``update``/…) on plan
attributes.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "clear", "update",
    "setdefault", "popitem", "add", "discard", "sort", "reverse",
    "normalize", "append_alloc", "append_stopped_alloc",
    "append_preempted_alloc", "append_lost_alloc",
}


def _base_name(node: ast.AST) -> str | None:
    """Leftmost Name of an Attribute/Subscript chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _root_is_plan_attr(node: ast.AST, tainted: set[str]) -> bool:
    """True when the chain bottoms out in ``<tainted>.<attr>`` — i.e. the
    expression is (a view into) one of the plan's containers."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        if isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id in tainted
            ):
                return True
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        else:  # Call, e.g. plan.node_update.values()
            node = node.func
    return False


class _FuncVisitor(ScopedVisitor):
    """Per-function taint tracking; the scope stack is pre-seeded by the
    module walker."""

    def __init__(self, relpath: str, tainted: set[str]):
        super().__init__(relpath)
        self.tainted = tainted

    # -- taint propagation -------------------------------------------------
    def _taint_target(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._taint_target(elt)

    def visit_Assign(self, node: ast.Assign) -> None:
        value_tainted = (
            isinstance(node.value, ast.Name) and node.value.id in self.tainted
        ) or _root_is_plan_attr(node.value, self.tainted)
        for target in node.targets:
            if isinstance(target, ast.Attribute):
                self._check_attr_store(target)
            elif isinstance(target, ast.Subscript):
                self._check_subscript_store(target)
            elif value_tainted:
                self._taint_target(target)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if _root_is_plan_attr(node.iter, self.tainted):
            self._taint_target(node.target)
        self.generic_visit(node)

    def visit_comprehension(self, node: ast.comprehension) -> None:
        if _root_is_plan_attr(node.iter, self.tainted):
            self._taint_target(node.target)
        self.generic_visit(node)

    # -- violation checks --------------------------------------------------
    def _check_attr_store(self, target: ast.Attribute) -> None:
        base = _base_name(target.value)
        if base in self.tainted or _root_is_plan_attr(
            target.value, self.tainted
        ):
            self.add(
                "NTA004",
                target,
                f"mutation of submitted plan object: "
                f"{base or '<expr>'}.{target.attr} = ... "
                f"(the applier must build PlanResult copies)",
            )

    def _check_subscript_store(self, target: ast.Subscript) -> None:
        base = _base_name(target.value)
        if base in self.tainted or _root_is_plan_attr(
            target.value, self.tainted
        ):
            self.add(
                "NTA004",
                target,
                "mutation of submitted plan container "
                "(the applier must build PlanResult copies)",
            )

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.target, ast.Attribute):
            self._check_attr_store(node.target)
        elif isinstance(node.target, ast.Subscript):
            self._check_subscript_store(node.target)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _MUTATORS
            and (
                (isinstance(func.value, ast.Name)
                 and func.value.id in self.tainted)
                or _root_is_plan_attr(func.value, self.tainted)
            )
        ):
            self.add(
                "NTA004",
                node,
                f"mutating call .{func.attr}() on submitted plan object",
            )
        self.generic_visit(node)


class _ModuleWalker(ScopedVisitor):
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        tainted = set()
        for arg in node.args.args + node.args.kwonlyargs:
            ann = dotted_name(arg.annotation) if arg.annotation else None
            if arg.arg == "plan" or (ann or "").split(".")[-1] == "Plan":
                tainted.add(arg.arg)
        if tainted:
            # the taint visitor walks the whole subtree (closures inherit
            # the taint), so don't descend again from here
            fv = _FuncVisitor(self.relpath, tainted)
            fv._scope = self._scope + [node.name]
            for stmt in node.body:
                fv.visit(stmt)
            self.findings.extend(fv.findings)
        else:
            self._push(node.name, node)


class PlanMutationAfterSubmit(Rule):
    id = "NTA004"
    title = "no mutation of plan/alloc structs inside the plan applier"

    def applies_to(self, relpath: str) -> bool:
        return relpath == "nomad_tpu/broker/plan_apply.py"

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _ModuleWalker(relpath)
        v.visit(tree)
        return v.findings
