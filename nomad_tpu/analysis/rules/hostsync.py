"""NTA002 — no host syncs inside jit-compiled device kernels.

A ``.item()``, a Python ``float()``/``int()`` on a traced value, an
``np.*`` call, or a Python loop over node arrays inside a jitted kernel
either fails tracing outright or — worse — silently forces a device→host
round trip per step and turns the one-pass placement kernel back into the
reference's sequential walk. The batch kernels must stay trace-pure.

Scope: ``nomad_tpu/device/score.py`` and ``nomad_tpu/device/preempt.py``.
A function counts as jitted when decorated with ``jax.jit``,
``functools.partial(jax.jit, ...)``, or the trace-counting wrapper
``traced_jit`` / ``backend.traced_jit`` (same forms). Everything lexically
inside a jitted function — including nested defs handed to ``lax.scan`` /
``vmap`` — is traced, so the whole subtree is checked.

``for x in range(...)`` is allowed: static-bound unrolling is the idiom
the chunked kernels rely on. Any other ``for``/``while`` is flagged —
data-dependent loops belong in ``lax.scan`` / ``fori_loop``.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_JIT_NAMES = {
    "jax.jit",
    "jit",
    "traced_jit",
    "backend.traced_jit",
    "utils.backend.traced_jit",
}

_CAST_BUILTINS = {"float", "int", "bool", "complex"}


def _is_jit_decorator(dec: ast.AST) -> bool:
    name = dotted_name(dec)
    if name in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if fname in _JIT_NAMES:
            return True  # jax.jit(...) / traced_jit(...) with options
        if fname in ("functools.partial", "partial"):
            return bool(dec.args) and dotted_name(dec.args[0]) in _JIT_NAMES
    return False


def _is_range_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "range"
    )


class _KernelVisitor(ScopedVisitor):
    """Walks the body of one jitted function (scope stack pre-seeded)."""

    def visit_Call(self, node: ast.Call) -> None:
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
        ):
            self.add("NTA002", node, "host sync: .item() inside a jitted kernel")
        name = dotted_name(node.func)
        if name:
            if name.split(".")[0] in ("np", "numpy", "onp"):
                self.add(
                    "NTA002",
                    node,
                    f"host round trip: {name}() inside a jitted kernel "
                    f"(use jnp/lax)",
                )
            elif (
                name in _CAST_BUILTINS
                and len(node.args) == 1
                and not isinstance(node.args[0], ast.Constant)
            ):
                self.add(
                    "NTA002",
                    node,
                    f"host sync: {name}() on a traced value inside a "
                    f"jitted kernel",
                )
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if not _is_range_call(node.iter):
            self.add(
                "NTA002",
                node,
                "Python for-loop over traced values inside a jitted kernel "
                "(use lax.scan/fori_loop or a static range)",
            )
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self.add(
            "NTA002",
            node,
            "Python while-loop inside a jitted kernel "
            "(use lax.while_loop)",
        )
        self.generic_visit(node)


class _Finder(ScopedVisitor):
    """Finds jitted top-level or nested functions and hands their bodies
    to the kernel visitor."""

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if any(_is_jit_decorator(d) for d in node.decorator_list):
            kv = _KernelVisitor(self.relpath)
            kv._scope = self._scope + [node.name]
            for stmt in node.body:
                kv.visit(stmt)
            self.findings.extend(kv.findings)
        else:
            self._push(node.name, node)


class HostSyncInJitKernel(Rule):
    id = "NTA002"
    title = "no host syncs inside jit-compiled device kernels"

    def applies_to(self, relpath: str) -> bool:
        return relpath in (
            "nomad_tpu/device/score.py",
            "nomad_tpu/device/preempt.py",
        )

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _Finder(relpath)
        v.visit(tree)
        return v.findings
