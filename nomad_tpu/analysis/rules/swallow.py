"""NTA003 — no silent exception swallows in server/broker/state code.

A worker thread that eats an exception leaves dequeued evals unacked
forever (the broker has no redelivery deadline) and a state-store path
that eats one can ship a half-applied snapshot downstream — both failure
modes are invisible until throughput quietly halves. Every handler in
these modules must leave a trace: a log call, a metrics bump (e.g.
``count_swallowed`` from ``utils/metrics.py``), or a re-raise.

Flagged:
- any handler whose body is only ``pass``/``continue``/``...`` (whatever
  the caught type — even a narrow catch deserves one counter bump), and
- any broad catch (``except:``, ``except Exception``, ``BaseException``)
  that neither logs, nor counts, nor raises.

Scope: ``nomad_tpu/server/``, ``nomad_tpu/broker/``, ``nomad_tpu/state/``.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_LOG_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log",
}
_METRIC_METHODS = {"incr", "set_gauge", "measure", "count_swallowed"}
_BROAD = {"Exception", "BaseException"}


def _exc_names(node: ast.AST | None) -> set[str]:
    if node is None:
        return set()  # bare except
    if isinstance(node, ast.Tuple):
        return {n for e in node.elts for n in _exc_names(e)}
    name = dotted_name(node)
    return {name.split(".")[-1]} if name else set()


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    return bool(_exc_names(handler.type) & _BROAD)


def _observes(handler: ast.ExceptHandler) -> bool:
    """Does the handler body log, count, or raise?"""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            leaf = name.split(".")[-1]
            if leaf in _METRIC_METHODS:
                return True
            if isinstance(node.func, ast.Attribute) and leaf in _LOG_METHODS:
                return True
    return False


def _pass_only(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


class _Visitor(ScopedVisitor):
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        caught = (
            "bare except"
            if node.type is None
            else f"except {', '.join(sorted(_exc_names(node.type))) or '?'}"
        )
        if _pass_only(node) and not _observes(node):
            self.add(
                "NTA003",
                node,
                f"silent swallow: {caught} with pass-only body "
                f"(log at debug or bump a swallowed_errors counter)",
            )
        elif _is_broad(node) and not _observes(node):
            self.add(
                "NTA003",
                node,
                f"silent swallow: {caught} neither logs, counts, nor "
                f"re-raises",
            )
        self.generic_visit(node)


class SilentExceptionSwallow(Rule):
    id = "NTA003"
    title = "no silent exception swallows in server/broker/state"

    def applies_to(self, relpath: str) -> bool:
        return (
            relpath.startswith("nomad_tpu/server/")
            or relpath.startswith("nomad_tpu/broker/")
            or relpath.startswith("nomad_tpu/state/")
        )

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _Visitor(relpath)
        v.visit(tree)
        return v.findings
