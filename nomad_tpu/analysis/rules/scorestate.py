"""NTA019 — cached score state mutates only through the refresh API.

``device/cache.py`` owns the persisted score-state double buffer
(``ScoreState``): device-resident score inputs plus a bitwise host
mirror, advanced generation-by-generation through ``score_view`` /
``score_commit`` / ``score_abort``. The incremental-rescoring pin —
patched passes bit-identical to from-scratch — holds exactly because
every mutation flows through that API: the mirror is updated in the
same locked region as the device patch, and generations are immutable
once staged. A device or scheduler module that writes the cached
tensors directly (``ct.score_cache = ...``, ``state.used_host[...] =
...``, rebinding ``device_capacity``) desynchronizes mirror and device
bytes, and the divergence only surfaces passes later as a wrong reused
row — the least debuggable failure this subsystem can produce.

Flagged: any assignment, augmented assignment, or ``del`` whose target
is an attribute named ``used_dev``, ``used_host``, ``score_cache``,
``score_state``, or ``device_capacity`` inside ``nomad_tpu/device/``
or ``nomad_tpu/scheduler/`` — including subscripted forms like
``x.used_host[i] = ...``.

Exempt: ``device/cache.py`` itself (it IS the refresh API) and
``device/flatten.py`` (the dataclass declares the ``score_cache`` /
``device_capacity`` attachment points the cache populates).
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor

_SCOPES = ("nomad_tpu/device/", "nomad_tpu/scheduler/")
_EXEMPT = (
    "nomad_tpu/device/cache.py",
    "nomad_tpu/device/flatten.py",
)

_PROTECTED_ATTRS = (
    "used_dev",
    "used_host",
    "score_cache",
    "score_state",
    "device_capacity",
)


def _protected_attr(target: ast.AST) -> str | None:
    """Attribute name if ``target`` writes a protected attribute,
    unwrapping subscripts (``x.used_host[i]`` mutates ``used_host``)."""
    node = target
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in _PROTECTED_ATTRS:
        return node.attr
    return None


class _ScoreStateVisitor(ScopedVisitor):
    def _check_targets(self, node: ast.AST, targets) -> None:
        for t in targets:
            attr = _protected_attr(t)
            if attr is not None:
                self.add(
                    "NTA019",
                    node,
                    f"direct write to cached score state .{attr}: mutate "
                    "through the DeviceStateCache refresh API (score_view/"
                    "score_commit/score_abort) so the device bytes and the "
                    "generation mirror stay bitwise in lockstep",
                )

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_targets(node, node.targets)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_targets(node, [node.target])
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        self._check_targets(node, node.targets)
        self.generic_visit(node)


class ScoreStateDiscipline(Rule):
    id = "NTA019"
    title = "cached score state mutates only through the refresh API"

    def applies_to(self, relpath: str) -> bool:
        if relpath in _EXEMPT:
            return False
        return relpath.startswith(_SCOPES)

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _ScoreStateVisitor(relpath)
        v.visit(tree)
        return v.findings
