"""NTA001 — no wall-clock or unseeded randomness in scoring/plan-apply.

Constraint-based schedulers live or die by reproducible scoring: the same
snapshot must always produce the same plan, or replay debugging, the
score-parity suite, and the applier's optimistic-conflict accounting all
stop meaning anything. Wall-clock reads and unseeded RNG inside the
scoring path are the two mechanical ways that property silently dies.

Scope: ``nomad_tpu/scheduler/``, ``nomad_tpu/device/``, and
``nomad_tpu/broker/plan_apply.py``. The eval broker's nack timers and the
server's heartbeat TTLs are real time by *design* and stay out of scope.

Allowed: ``time.perf_counter`` / ``time.monotonic`` (metrics timing, not
scoring inputs), seeded ``np.random.default_rng(seed)``, and ``jax.random``
(explicit key discipline). An injectable-clock *reference* (``clock or
time.time``) is fine — only calls are flagged.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_FORBIDDEN_CALLS = {
    "time.time": "wall-clock read",
    "time.time_ns": "wall-clock read",
    "datetime.now": "wall-clock read",
    "datetime.utcnow": "wall-clock read",
    "datetime.datetime.now": "wall-clock read",
    "datetime.datetime.utcnow": "wall-clock read",
    "datetime.date.today": "wall-clock read",
}

_RANDOM_MODULES = ("random.", "np.random.", "numpy.random.")


class _Visitor(ScopedVisitor):
    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        if name:
            reason = _FORBIDDEN_CALLS.get(name)
            if reason is None:
                for prefix in _RANDOM_MODULES:
                    if name.startswith(prefix):
                        # seeded generator construction is deterministic
                        if name.endswith(".default_rng") and node.args:
                            break
                        reason = "unseeded randomness"
                        break
        else:
            reason = None
        if name and reason:
            self.add(
                "NTA001",
                node,
                f"{reason}: {name}() in a scoring/plan-apply path "
                f"(inject a clock/seed instead)",
            )
        self.generic_visit(node)


class WallClockInScoringPath(Rule):
    id = "NTA001"
    title = "no wall-clock/randomness in scheduler scoring or plan apply"

    def applies_to(self, relpath: str) -> bool:
        return (
            relpath.startswith("nomad_tpu/scheduler/")
            or relpath.startswith("nomad_tpu/device/")
            or relpath == "nomad_tpu/broker/plan_apply.py"
        )

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _Visitor(relpath)
        v.visit(tree)
        return v.findings
