"""NTA021 — live migration flows only through its sanctioned seam.

The migration auction (device/migrate.py ``migrate_plan_kernel`` and
its host oracle ``oracle_migrate_plan``) prices moves against a
used-only-increases capacity model — that model IS invariant law 16's
mid-move conservation guarantee, but only if every planned move then
rides the two-phase protocol in ``server/defrag.py``: replacement
placed through a confirmed lane claim and the serialized plan applier
first, source stopped second, recovery scan bounding half-moves to one
cycle. A scheduler or server module that calls the kernel directly —
or assembles its own batch with ``build_defrag_batch`` — gets a move
list with none of that sequencing: sources could free before
replacements commit (capacity conservation broken mid-flight), moves
could bypass the lane-owner commit path, and the ``nomad.migrate.*``
ledger law 16 audits would never be written.

Flagged: any call whose dotted leaf is ``migrate_plan_kernel``,
``oracle_migrate_plan``, ``build_defrag_batch``, or ``run_defrag_ab``
inside ``nomad_tpu/scheduler/`` or ``nomad_tpu/server/``.

Exempt: ``scheduler/migrate.py`` (the seam itself — batch assembly,
oracle cross-check, and the ``bench.py defrag`` A/B harness) and
``server/defrag.py`` (the controller that owns the two-phase protocol).
``nomad_tpu/device/`` is out of scope, as for NTA016: the rule polices
dispatch, not implementation or parity pinning.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_SCOPES = ("nomad_tpu/scheduler/", "nomad_tpu/server/")
_EXEMPT = (
    "nomad_tpu/scheduler/migrate.py",
    "nomad_tpu/server/defrag.py",
)

_MIGRATE_LEAVES = (
    "migrate_plan_kernel",
    "oracle_migrate_plan",
    "build_defrag_batch",
    "run_defrag_ab",
)


class _MigrateVisitor(ScopedVisitor):
    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in _MIGRATE_LEAVES:
            self.add(
                "NTA021",
                node,
                f"direct migration-plane invocation {leaf}(...): route "
                "through server/defrag.py (the DefragController) so the "
                "two-phase place-first sequencing, lane-claim commit "
                "path, and law-16 conservation ledger stay on the path",
            )
        self.generic_visit(node)


class MigrationSeamDiscipline(Rule):
    id = "NTA021"
    title = "migration kernel invoked only through the defrag seam"

    def applies_to(self, relpath: str) -> bool:
        if relpath in _EXEMPT:
            return False
        return relpath.startswith(_SCOPES)

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _MigrateVisitor(relpath)
        v.visit(tree)
        return v.findings
