"""NTA014 — raw score arrays cross to operators only via the explain seam.

Placement provenance has exactly one sanctioned exit: ``obs/explain.py``
turns the kernels' dense score state into ``PlacementExplanation``
structs with named components, a pinned schema version, and bounded
top-k candidate lists. A scheduler or server module that serializes the
raw arrays instead — ``res.scores.tolist()`` into a log line,
``json.dumps``/``encode``/``print`` of ``node_rows`` or ``finals`` —
leaks an unbounded, unversioned, component-free dump: N-node fleets put
megabytes on the wire, the shape silently changes with every kernel
refactor, and downstream tooling starts parsing what was never an
interface. Route it through the explain seam, where the schema smoke
test pins the shape.

Flagged, inside ``nomad_tpu/scheduler/`` and ``nomad_tpu/server/``:

- ``X.<attr>.tolist()`` / ``.tobytes()`` / ``.tofile()`` where
  ``<attr>`` is a raw score field (``scores``, ``node_rows``,
  ``finals``, ``overflow_rows``, ``overflow_scores``).
- a bare ``X.<attr>`` of those names passed directly to
  ``json.dumps(...)``, ``encode(...)``, or ``print(...)``.

Not flagged: numeric use of the arrays (indexing, argmax, comparisons)
— the rule polices *egress*, not computation. ``obs/`` and ``device/``
are out of scope: explain.py IS the seam and the kernels own their
arrays.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_SCOPES = ("nomad_tpu/scheduler/", "nomad_tpu/server/")

# PlacementResult / kernel-local dense state (device/score.py)
_RAW_SCORE_ATTRS = (
    "scores",
    "node_rows",
    "finals",
    "overflow_rows",
    "overflow_scores",
)

_SERIALIZERS = ("tolist", "tobytes", "tofile")
_DUMP_SINKS = ("dumps", "encode", "print")


def _raw_attr_leaf(node: ast.expr) -> str:
    """`` res.scores`` → ``scores`` when it names a raw score field."""
    if isinstance(node, ast.Attribute) and node.attr in _RAW_SCORE_ATTRS:
        return node.attr
    return ""


class _ScoreDumpVisitor(ScopedVisitor):
    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        # res.scores.tolist() — serializing the array itself
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _SERIALIZERS
        ):
            leaf = _raw_attr_leaf(func.value)
            if leaf:
                self.add(
                    "NTA014",
                    node,
                    f"raw score array dump .{leaf}.{func.attr}(): "
                    "operator-facing score data must cross through "
                    "obs/explain.py (PlacementExplanation), not as raw "
                    "arrays",
                )
        # json.dumps(res.node_rows) / encode(res.scores) / print(finals)
        name = dotted_name(func) or ""
        if name.rsplit(".", 1)[-1] in _DUMP_SINKS:
            for arg in node.args:
                leaf = _raw_attr_leaf(arg)
                if leaf:
                    self.add(
                        "NTA014",
                        node,
                        f"raw score array {leaf!r} passed to "
                        f"{name}(...): serialize placement provenance "
                        "via obs/explain.py, not raw kernel arrays",
                    )
        self.generic_visit(node)


class ScoreDumpDiscipline(Rule):
    id = "NTA014"
    title = "raw score arrays exit only through the explain seam"

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(_SCOPES)

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _ScoreDumpVisitor(relpath)
        v.visit(tree)
        return v.findings
