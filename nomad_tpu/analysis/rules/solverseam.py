"""NTA016 — the CP solver is invoked only through its sanctioned seam.

The CP dispatcher's device kernel (device/cp.py ``cp_place_kernel`` and
its host oracle ``oracle_cp_place``) carries load-bearing invariants
that live OUTSIDE the kernel: ``scheduler/cp.py`` is where score rows
are assembled through the registry's ``score_group``, where the
circuit-breaker fallback to greedy binpack is decided, where the
``cp.round_perturb`` chaos hook feeds initial prices, and where the
law-13 conservation counters (``nomad.cp.*``) are recorded. A scheduler
or server module that calls the kernel directly — or constructs
``CpPlacementKernel(...)`` outside the algorithm registry — bypasses
all of that: placements commit with no conservation ledger, no breaker
protection, and score rows that may not match what binpack ranks by
(breaking the A/B's like-for-like contract).

Flagged: any call whose dotted leaf is ``cp_place_kernel``,
``oracle_cp_place``, ``CpPlacementKernel``, or ``build_cp_batch``
inside ``nomad_tpu/scheduler/`` or ``nomad_tpu/server/``.

Exempt: ``scheduler/algorithms.py`` (the registry constructs the kernel
wrapper) and ``scheduler/cp.py`` (the seam itself — batch assembly,
oracle cross-checks, and the A/B harness live there). ``nomad_tpu/
device/`` is out of scope, as for NTA013: the rule polices dispatch,
not implementation or parity pinning.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_SCOPES = ("nomad_tpu/scheduler/", "nomad_tpu/server/")
_EXEMPT = (
    "nomad_tpu/scheduler/algorithms.py",
    "nomad_tpu/scheduler/cp.py",
)

_SOLVER_LEAVES = (
    "cp_place_kernel",
    "oracle_cp_place",
    "CpPlacementKernel",
    "build_cp_batch",
)


class _SolverVisitor(ScopedVisitor):
    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in _SOLVER_LEAVES:
            self.add(
                "NTA016",
                node,
                f"direct CP solver invocation {leaf}(...): route through "
                "scheduler/algorithms.py (the cp-pack plugin) so breaker "
                "fallback, chaos perturbation, and law-13 conservation "
                "accounting stay on the path",
            )
        self.generic_visit(node)


class SolverSeamDiscipline(Rule):
    id = "NTA016"
    title = "CP solver invoked only through the algorithm registry seam"

    def applies_to(self, relpath: str) -> bool:
        if relpath in _EXEMPT:
            return False
        return relpath.startswith(_SCOPES)

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _SolverVisitor(relpath)
        v.visit(tree)
        return v.findings
