"""Rule registry. Each rule module registers its Rule subclass here; the
engine instantiates every registered rule per run. Order is the report
order for same-line findings, so keep it sorted by rule id."""

from .determinism import WallClockInScoringPath  # noqa: E402
from .hostsync import HostSyncInJitKernel  # noqa: E402
from .swallow import SilentExceptionSwallow  # noqa: E402
from .planfreeze import PlanMutationAfterSubmit  # noqa: E402
from .lockfields import LockDiscipline  # noqa: E402
from .spans import SpanCoverage  # noqa: E402
from .mergedsubmit import MergedSubmitDiscipline  # noqa: E402
from .wallclock import BareWallClockInBrokerServer  # noqa: E402
from .blocking import BlockingWithoutTimeout  # noqa: E402
from .laneowner import LaneOwnerDiscipline  # noqa: E402
from .accumulation import UnboundedAccumulation  # noqa: E402
from .admissiongate import AdmissionGateDiscipline  # noqa: E402
from .algorithmseam import AlgorithmSeamDiscipline  # noqa: E402
from .scoredump import ScoreDumpDiscipline  # noqa: E402
from .shardingseam import ShardingSeamDiscipline  # noqa: E402
from .solverseam import SolverSeamDiscipline  # noqa: E402
from .kernelseam import KernelSeamDiscipline  # noqa: E402
from .provenance import ConstantProvenanceDiscipline  # noqa: E402
from .scorestate import ScoreStateDiscipline  # noqa: E402
from .topologyseam import TopologySeamDiscipline  # noqa: E402
from .migrationseam import MigrationSeamDiscipline  # noqa: E402

REGISTRY = [
    WallClockInScoringPath,  # NTA001
    HostSyncInJitKernel,  # NTA002
    SilentExceptionSwallow,  # NTA003
    PlanMutationAfterSubmit,  # NTA004
    LockDiscipline,  # NTA005
    SpanCoverage,  # NTA006
    MergedSubmitDiscipline,  # NTA007
    BareWallClockInBrokerServer,  # NTA008
    BlockingWithoutTimeout,  # NTA009
    LaneOwnerDiscipline,  # NTA010
    UnboundedAccumulation,  # NTA011
    AdmissionGateDiscipline,  # NTA012
    AlgorithmSeamDiscipline,  # NTA013
    ScoreDumpDiscipline,  # NTA014
    ShardingSeamDiscipline,  # NTA015
    SolverSeamDiscipline,  # NTA016
    KernelSeamDiscipline,  # NTA017
    ConstantProvenanceDiscipline,  # NTA018
    ScoreStateDiscipline,  # NTA019
    TopologySeamDiscipline,  # NTA020
    MigrationSeamDiscipline,  # NTA021
]

__all__ = ["REGISTRY"]
