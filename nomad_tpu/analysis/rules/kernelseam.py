"""NTA017 — device kernels go through the traced_jit seam.

``utils/backend.py`` owns kernel compilation: ``traced_jit`` is the one
wrapper that counts traces (the retrace-budget watchdog and the breaker
read those counters), registers the kernel with the jaxlint analyzer
(original body + abstract call specs, so JXL001-006 can re-trace it),
and threads chaos/profiling hooks. A bare ``jax.jit`` anywhere else in
the package produces a kernel that is invisible to ALL of that: it
never appears in ``nomad-tpu analyze kernels``, its retraces don't trip
the budget checker, and the fleet-wide fingerprint invariants silently
exclude it. The failure mode is not a crash — it is an unaudited
program shipping alongside nine audited ones.

Flagged, anywhere in ``nomad_tpu/``: any reference to the dotted name
``jax.jit`` (call, decorator, or ``functools.partial(jax.jit, ...)``
argument) and any ``from jax import jit``.

Exempt: ``utils/backend.py`` — the seam itself wraps ``jax.jit`` by
construction.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_SCOPES = ("nomad_tpu/",)
_EXEMPT = ("nomad_tpu/utils/backend.py",)

_MSG = (
    "bare jax.jit: compile device kernels with utils/backend.py "
    "traced_jit so the kernel is trace-counted, budget-audited, and "
    "visible to the jaxlint analyzer"
)


class _JitVisitor(ScopedVisitor):
    def visit_Attribute(self, node: ast.Attribute) -> None:
        if dotted_name(node) == "jax.jit":
            self.add("NTA017", node, _MSG)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "jax" and any(
            a.name == "jit" for a in node.names
        ):
            self.add(
                "NTA017",
                node,
                "from jax import jit: " + _MSG,
            )
        self.generic_visit(node)


class KernelSeamDiscipline(Rule):
    id = "NTA017"
    title = "device kernels go through the traced_jit seam"

    def applies_to(self, relpath: str) -> bool:
        if relpath in _EXEMPT:
            return False
        return relpath.startswith(_SCOPES)

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _JitVisitor(relpath)
        v.visit(tree)
        return v.findings
