"""NTA007 — the worker's batch path must submit through the merged queue.

The coalesced commit train exists because a 16-deep batched device pass
that commits one plan at a time serializes into 16 applier round trips,
16 FSM entries, and 16 store index bumps — the per-eval commit train the
merged path collapses into one (`PlanQueue.enqueue_merged` →
`PlanApplier.apply_merged`, one MERGED_PLAN_RESULT raft entry). A direct
per-eval submit sneaking back into the batch path silently reintroduces
the train: everything still works, the bench just quietly loses its
plans_per_commit ≈ batch-depth property.

Flagged: inside ``Worker._run_batch`` / ``Worker._commit_batch*`` (the
batch pipeline), any call whose dotted name is or ends in
``.submit_plan`` or ``plan_queue.enqueue`` — the per-eval submission
entry points. ``enqueue_merged`` is the sanctioned path. The individual
fallback (``_run_one`` and everything under it) is exempt: stale members
are SUPPOSED to retry through the single-plan path.

Scope: ``server/worker.py`` only — schedulers and direct (non-batch)
planner callers legitimately use ``submit_plan``.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_WORKER_MODULE = "nomad_tpu/server/worker.py"

# the batch pipeline's functions: the device-pass driver and the commit
# thread it hands off to (prefix-matched so helpers split out of the
# commit path stay covered)
_BATCH_FUNCS = ("_run_batch", "_commit_batch")


class _Visitor(ScopedVisitor):
    def _in_batch_path(self) -> bool:
        return any(
            part.startswith(_BATCH_FUNCS) for part in self._scope
        )

    def visit_Call(self, node: ast.Call) -> None:
        if self._in_batch_path():
            name = dotted_name(node.func) or ""
            per_eval_submit = (
                name == "submit_plan"
                or name.endswith(".submit_plan")
                or name.endswith("plan_queue.enqueue")
            )
            if per_eval_submit:
                self.add(
                    "NTA007",
                    node,
                    f"per-eval {name}(...) in the worker batch path: the "
                    f"batched pass must coalesce through "
                    f"plan_queue.enqueue_merged so one pass stays one "
                    f"applier commit",
                )
        self.generic_visit(node)


class MergedSubmitDiscipline(Rule):
    id = "NTA007"
    title = "batched passes submit through the merged plan queue"

    def applies_to(self, relpath: str) -> bool:
        return relpath == _WORKER_MODULE

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _Visitor(relpath)
        v.visit(tree)
        return v.findings
