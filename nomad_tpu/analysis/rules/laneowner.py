"""NTA010 — the worker batch path mutates placement state only through
the lane-owner API.

Deterministic lane ownership (server/lanes.py) makes multi-worker commits
conflict-free *by construction* — but only while every write in the batch
pipeline goes through the sanctioned seams: the worker's own overlay via
``self._my_overlay()``, deltas tagged with ``writer=`` (the overlay's
cross-lane-write refusal keys on it), cross-lane nodes via the
``lane_claims`` reserve→confirm handshake, and committed state via the
merged plan queue. A direct write that bypasses any of these compiles,
runs, and passes a 1-worker test — then silently reintroduces exactly the
multi-worker race the lanes were built to make impossible.

Flagged inside ``Worker._run_batch`` / ``Worker._commit_batch*`` (the
batch pipeline, NTA007's scope):

- any reference to ``placement_overlay`` — the shared container must be
  reached through ``_my_overlay()`` (the accessor itself is the one
  sanctioned reader);
- ``.add_delta(...)`` calls without a ``writer=`` keyword — an untagged
  delta is invisible to the overlay's lane-ownership check;
- ``store.upsert_* / store.delete_*`` calls — workers land state through
  the plan queue's verified commit, never by writing the store directly.

Scope: ``server/worker.py`` only, same as NTA007 — schedulers and the
applier legitimately touch overlays and the store through their own
contracts.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_WORKER_MODULE = "nomad_tpu/server/worker.py"

# the batch pipeline's functions (prefix-matched, NTA007's scoping)
_BATCH_FUNCS = ("_run_batch", "_commit_batch")

# the one sanctioned reader of the shared overlay container
_ACCESSOR = "_my_overlay"

_STORE_MUTATORS = ("upsert_", "delete_")


class _Visitor(ScopedVisitor):
    def _in_batch_path(self) -> bool:
        if any(part == _ACCESSOR for part in self._scope):
            return False
        return any(
            part.startswith(_BATCH_FUNCS) for part in self._scope
        )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self._in_batch_path() and node.attr == "placement_overlay":
            self.add(
                "NTA010",
                node,
                "direct placement_overlay access in the worker batch "
                "path: go through self._my_overlay() so each batching "
                "worker writes its OWN lane overlay",
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self._in_batch_path():
            name = dotted_name(node.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            if leaf == "add_delta" and not any(
                kw.arg == "writer" for kw in node.keywords
            ):
                self.add(
                    "NTA010",
                    node,
                    "add_delta(...) without writer= in the worker batch "
                    "path: untagged deltas bypass the overlay's "
                    "cross-lane write refusal",
                )
            if (
                "store." in f"{name}."
                and any(leaf.startswith(p) for p in _STORE_MUTATORS)
            ):
                self.add(
                    "NTA010",
                    node,
                    f"direct store mutation {name}(...) in the worker "
                    "batch path: placements land through the merged "
                    "plan queue's verified commit, not store writes",
                )
        self.generic_visit(node)


class LaneOwnerDiscipline(Rule):
    id = "NTA010"
    title = "batch-path placement writes go through the lane-owner API"

    def applies_to(self, relpath: str) -> bool:
        return relpath == _WORKER_MODULE

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _Visitor(relpath)
        v.visit(tree)
        return v.findings
