"""NTA018 — admission/hetero thresholds carry calibration provenance.

The calibration plane (``nomad_tpu/obs/calibrate.py``) exists so every
operational threshold answers "where did this number come from?" —
``default``, ``probe``, or ``learned``. A bare numeric literal compared
against a runtime quantity in ``server/admission.py`` or
``scheduler/hetero.py`` is a threshold with no provenance: it can't be
overridden by a saturation probe, never shows up in
``/v1/agent/calibration``, and silently drifts from the measured
envelope. Route it through ``CalibrationTable`` (the
``_default_config()`` seam in admission, the throughput seam in hetero)
instead.

Two shapes are flagged:

- a non-structural numeric literal used directly as an ``ast.Compare``
  operand (structural values — 0, 0.0, 1, 1.0, -1 — encode emptiness /
  identity / sentinels, not tuned thresholds, and stay legal);
- a module-level dict literal with three or more numeric values bound
  to a name containing ``DEFAULT`` or ``THRESHOLD`` — a constants table
  that bypasses the calibration table's provenance tracking.

Pre-existing offenders (the ``tier_of`` priority-tier cutpoints, which
are protocol constants shared with clients rather than tunables) live
in the ratchet baseline.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor

# emptiness / identity / sentinel values: comparisons against these are
# structural control flow, not tuned thresholds
_STRUCTURAL = {0, 0.0, 1, 1.0, -1}
_NAME_MARKERS = ("DEFAULT", "THRESHOLD")
_MIN_DICT_NUMERICS = 3


def _literal_value(node: ast.AST):
    """Numeric value of a (possibly negated) constant literal, or None.
    bools are constants too but never thresholds."""
    if (
        isinstance(node, ast.UnaryOp)
        and isinstance(node.op, ast.USub)
        and isinstance(node.operand, ast.Constant)
    ):
        v = node.operand.value
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        return -v
    if isinstance(node, ast.Constant):
        v = node.value
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        return v
    return None


class _Visitor(ScopedVisitor):
    def visit_Compare(self, node: ast.Compare) -> None:
        for operand in [node.left, *node.comparators]:
            value = _literal_value(operand)
            if value is None or value in _STRUCTURAL:
                continue
            self.add(
                "NTA018",
                operand,
                f"bare numeric threshold {value!r} in a comparison — "
                "route it through the calibration table "
                "(obs/calibrate.py) so it carries provenance",
            )
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # only module-level bindings: a local dict inside a function is
        # plumbing, not a constants table
        if not self.qualname():
            for target in node.targets:
                if not isinstance(target, ast.Name):
                    continue
                name = target.id.upper()
                if not any(m in name for m in _NAME_MARKERS):
                    continue
                if not isinstance(node.value, ast.Dict):
                    continue
                numerics = sum(
                    1
                    for v in node.value.values
                    if _literal_value(v) is not None
                )
                if numerics >= _MIN_DICT_NUMERICS:
                    self.add(
                        "NTA018",
                        node,
                        f"module-level constants dict '{target.id}' holds "
                        f"{numerics} numeric defaults — source them from "
                        "the calibration table (obs/calibrate.py) so each "
                        "carries provenance",
                    )
        self.generic_visit(node)


class ConstantProvenanceDiscipline(Rule):
    id = "NTA018"
    title = "admission/hetero thresholds come from the calibration table"

    def applies_to(self, relpath: str) -> bool:
        return relpath in (
            "nomad_tpu/server/admission.py",
            "nomad_tpu/scheduler/hetero.py",
        )

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _Visitor(relpath)
        v.visit(tree)
        return v.findings
