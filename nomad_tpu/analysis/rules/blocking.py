"""NTA009 — no unbounded blocking primitives in server/rpc code.

A ``thread.join()`` with no timeout or a ``queue.get()`` with no timeout
turns a wedged peer into a wedged *server*: the shutdown path stalls
behind a worker stuck in a C call, an RPC reader blocks forever on a
half-closed socket, and the process survives SIGTERM only via SIGKILL —
losing the flight recorder and any in-flight acks. Every join/get in
these modules must carry a ``timeout=`` (and re-check its exit
condition in a loop if it needs to wait longer).

Flagged:
- ``<x>.join()`` with no ``timeout`` argument, and
- ``<x>.get()`` with no ``timeout`` argument — unless ``block`` is the
  constant ``False`` (non-blocking get never hangs).

``str.join(iterable)`` is not a hazard; calls with positional arguments
are skipped so only the zero-arg thread/process join shape is flagged.

Scope: ``nomad_tpu/server/``, ``nomad_tpu/rpc/``.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor


def _kw(node: ast.Call, name: str) -> ast.keyword | None:
    for kw in node.keywords:
        if kw.arg == name:
            return kw
    return None


class _Visitor(ScopedVisitor):
    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr == "join" and not node.args and _kw(node, "timeout") is None:
                self.add(
                    "NTA009",
                    node,
                    "unbounded .join(): pass timeout= and re-check "
                    "is_alive() in a loop (a wedged thread must not "
                    "wedge shutdown)",
                )
            elif attr == "get" and not node.args and _kw(node, "timeout") is None:
                block = _kw(node, "block")
                nonblocking = (
                    block is not None
                    and isinstance(block.value, ast.Constant)
                    and block.value.value is False
                )
                if not nonblocking:
                    self.add(
                        "NTA009",
                        node,
                        "unbounded queue.get(): pass timeout= (or "
                        "block=False) so a dead producer cannot hang "
                        "the consumer forever",
                    )
        self.generic_visit(node)


class BlockingWithoutTimeout(Rule):
    id = "NTA009"
    title = "no unbounded join()/queue.get() in server/rpc"

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("nomad_tpu/server/") or relpath.startswith(
            "nomad_tpu/rpc/"
        )

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _Visitor(relpath)
        v.visit(tree)
        return v.findings
