"""NTA011 — no unbounded in-memory accumulation in obs/broker/server.

A list or dict that only ever grows is a slow memory leak with a
latency tail: the steady-state soak (obs/loadgen.py) runs the cluster
for minutes at hundreds of events per second, and any per-event append
without an eviction bound eventually dominates RSS and GC pauses — the
exact failure the bounded LogHistogram/TimeSeriesRing plane
(utils/hist.py) exists to prevent. Every long-lived container in these
modules must have an eviction story: a cap-and-trim, a pop/del path, a
``deque(maxlen=...)``, or a bounded structure by construction.

Flagged, per class (``self.X``) and per module-level container:
- growth calls (``append``/``extend``/``insert``/``appendleft``/
  ``setdefault``/``add``) against an attribute or module-level
  container with **no** eviction evidence anywhere in the same class /
  module: ``pop``/``popitem``/``popleft``/``remove``/``clear``/
  ``discard``, a ``del x[...]`` (index or slice), or a rebuild
  assignment outside ``__init__``.
- containers initialized as ``deque(maxlen=...)`` or as bounded
  telemetry types (``LogHistogram``, ``TimeSeriesRing``) are bounded by
  construction and never flagged.

Scope: ``nomad_tpu/obs/``, ``nomad_tpu/broker/``, ``nomad_tpu/server/``.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_GROW = {"append", "extend", "insert", "appendleft", "setdefault", "add"}
_EVICT = {"pop", "popitem", "popleft", "remove", "clear", "discard"}
# bounded by construction: fixed-capacity telemetry primitives
_BOUNDED_TYPES = {"LogHistogram", "TimeSeriesRing"}
_CONTAINER_TYPES = {
    "list", "dict", "set", "defaultdict", "OrderedDict", "deque",
}


def _is_bounded_ctor(value: ast.AST) -> bool:
    """deque(maxlen=...) or a bounded telemetry type."""
    if not isinstance(value, ast.Call):
        return False
    name = (dotted_name(value.func) or "").split(".")[-1]
    if name in _BOUNDED_TYPES:
        return True
    if name == "deque":
        return any(kw.arg == "maxlen" for kw in value.keywords)
    return False


def _is_container_init(value: ast.AST) -> bool:
    if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        name = (dotted_name(value.func) or "").split(".")[-1]
        return name in _CONTAINER_TYPES
    return False


class _Visitor(ScopedVisitor):
    """One pass per class scope (plus the module scope for top-level
    containers): collect growth sites and eviction evidence, flag the
    growth sites whose target never sees an eviction."""

    def __init__(self, relpath: str, module_containers: set[str]):
        super().__init__(relpath)
        self._module_containers = module_containers
        self._class_stack: list[str] = []
        # (scope, target) → first growth call node
        self._grown: dict[tuple[str, str], ast.AST] = {}
        self._evicted: set[tuple[str, str]] = set()
        self._bounded: set[tuple[str, str]] = set()
        self._func_stack: list[str] = []
        # local name → tracked key, for `s = self.x.get(k)` /
        # `s = self.x[k]` aliases: an eviction through the alias
        # (s.clear()) credits the underlying container
        self._aliases: dict[tuple[str, str], tuple[str, str]] = {}

    def _cls(self) -> str:
        return self._class_stack[-1] if self._class_stack else ""

    def _target_key(self, obj: ast.AST) -> tuple[str, str] | None:
        """(scope, target) for direct ``self.X`` attributes and
        module-level containers. Deeper paths (``self.a.b``) belong to
        another object whose own class owns the eviction story; locals
        and other expressions return None."""
        name = dotted_name(obj)
        if not name:
            return None
        if (
            name.startswith("self.")
            and name.count(".") == 1
            and self._class_stack
        ):
            return (self._cls(), name)
        if "." not in name and name in self._module_containers:
            return ("", name)
        return None

    def _alias_key(self, obj: ast.AST) -> tuple[str, str] | None:
        """Resolve a bare local name through the alias map."""
        if isinstance(obj, ast.Name):
            return self._aliases.get((self._cls(), obj.id))
        return None

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self._push(node.name, node)
        self._class_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._func_stack.append(node.name)
        self._push(node.name, node)
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            key = self._target_key(node.func.value)
            if key is not None:
                if node.func.attr in _GROW:
                    self._grown.setdefault(key, node)
                elif node.func.attr in _EVICT:
                    self._evicted.add(key)
            elif node.func.attr in _EVICT:
                alias = self._alias_key(node.func.value)
                if alias is not None:
                    self._evicted.add(alias)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                key = self._target_key(t.value)
                if key is not None:
                    self._evicted.add(key)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            key = None
            if isinstance(t, ast.Subscript):
                # slice assignment (x[:] = ...) trims in place; keyed
                # assignment grows a dict
                if isinstance(t.slice, ast.Slice):
                    key = self._target_key(t.value)
                    if key is not None:
                        self._evicted.add(key)
                continue
            if isinstance(t, ast.Name):
                src = self._read_source_key(node.value)
                if src is not None:
                    self._aliases[(self._cls(), t.id)] = src
            key = self._target_key(t)
            if key is None:
                continue
            if _is_bounded_ctor(node.value):
                self._bounded.add(key)
            elif self._func_stack and self._func_stack[-1] != "__init__":
                # rebuild outside __init__ (e.g. x = [v for v in x if
                # keep(v)]) is an eviction path
                self._evicted.add(key)
        self.generic_visit(node)

    def _read_source_key(self, value: ast.AST) -> tuple[str, str] | None:
        """The tracked container a read expression drills into:
        ``self.x[k]``, ``self.x.get(k)``, ``self.x.setdefault(k, …)``."""
        if isinstance(value, ast.Subscript):
            return self._target_key(value.value)
        if (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and value.func.attr in ("get", "setdefault")
        ):
            return self._target_key(value.func.value)
        return None

    def findings_for_module(self) -> list[Finding]:
        for key, node in sorted(
            self._grown.items(), key=lambda kv: kv[1].lineno
        ):
            if key in self._evicted or key in self._bounded:
                continue
            scope, target = key
            where = f"{scope}.{target}" if scope else target
            self.add(
                "NTA011",
                node,
                f"unbounded accumulation: {where} only ever grows in "
                f"this {'class' if scope else 'module'} — cap it "
                f"(deque(maxlen=), trim-on-insert, LogHistogram/"
                f"TimeSeriesRing) or add an eviction path",
            )
        return self.findings


def _module_container_names(tree: ast.Module) -> set[str]:
    """Names bound at module top level to a list/dict/set — the only
    module-level targets the rule tracks (locals named the same inside
    functions don't alias these; growth is matched by name, which is
    the same heuristic scoping the repo's other rules use)."""
    out: set[str] = set()
    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = [
                t.id for t in stmt.targets if isinstance(t, ast.Name)
            ]
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            if isinstance(stmt.target, ast.Name):
                targets = [stmt.target.id]
            value = stmt.value
        else:
            continue
        if targets and _is_container_init(value) and not _is_bounded_ctor(
            value
        ):
            out.update(targets)
    return out


class UnboundedAccumulation(Rule):
    id = "NTA011"
    title = "no unbounded in-memory accumulation in obs/broker/server"

    def applies_to(self, relpath: str) -> bool:
        return (
            relpath.startswith("nomad_tpu/obs/")
            or relpath.startswith("nomad_tpu/broker/")
            or relpath.startswith("nomad_tpu/server/")
        )

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _Visitor(relpath, _module_container_names(tree))
        v.visit(tree)
        return v.findings_for_module()
