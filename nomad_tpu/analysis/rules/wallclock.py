"""NTA008 — broker/server/obs time flows through an injectable clock.

The chaos plane's clock-skew faults (nomad_tpu.chaos) only reach a
decision if that decision reads time through the injected clock: the
broker's unack-redelivery deadline, its delayed-eval heap, and the
heartbeater's TTL expiry are exactly the paths a skewed clock is meant
to stress. A bare ``time.time()`` or ``time.sleep()`` in
``nomad_tpu/broker/``, ``nomad_tpu/server/``, or ``nomad_tpu/obs/`` is
a decision the fault plane (and any deterministic replay) cannot steer,
so it is banned; use the ``clock=`` seam (``self._clock()``) the way
EvalBroker and NodeHeartbeater do, or take a ``sleep=`` callable. The
obs scope keeps the SLO collector and throughput-estimator windows
replayable under FakeClock; ``obs/loadgen.py`` is exempt — wall-clock
pacing of open-loop arrivals is the point there.

``time.monotonic``/``time.perf_counter`` for *measuring* (metrics
spans, wait-loop budgets in test helpers) stay legal — only ``time``
and ``sleep`` are scheduling decisions. Aliased imports
(``import time as _t``, ``from time import time, sleep``) are resolved
before matching; pre-existing offenders live in the ratchet baseline.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_BANNED_ATTRS = {"time", "sleep"}


class _Visitor(ScopedVisitor):
    def __init__(self, relpath: str):
        super().__init__(relpath)
        # local name → canonical dotted target, built from the module's
        # imports so aliasing can't dodge the rule
        self._module_aliases: dict[str, str] = {}  # "_t" → "time"
        self._func_aliases: dict[str, str] = {}  # "now" → "time.time"

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name == "time":
                self._module_aliases[alias.asname or "time"] = "time"
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time" and node.level == 0:
            for alias in node.names:
                if alias.name in _BANNED_ATTRS:
                    self._func_aliases[alias.asname or alias.name] = (
                        f"time.{alias.name}"
                    )
        self.generic_visit(node)

    def _resolve(self, node: ast.AST) -> str | None:
        name = dotted_name(node)
        if name is None:
            return None
        if name in self._func_aliases:
            return self._func_aliases[name]
        head, _, attr = name.rpartition(".")
        if head in self._module_aliases and attr in _BANNED_ATTRS:
            return f"time.{attr}"
        return None

    def visit_Call(self, node: ast.Call) -> None:
        target = self._resolve(node.func)
        if target is not None:
            self.add(
                "NTA008",
                node,
                f"bare {target}() in a broker/server scheduling path "
                "(thread a clock=/sleep= seam so chaos skew and replay "
                "can steer it)",
            )
        self.generic_visit(node)


class BareWallClockInBrokerServer(Rule):
    id = "NTA008"
    title = "broker/server/obs time must flow through an injectable clock"

    def applies_to(self, relpath: str) -> bool:
        if relpath == "nomad_tpu/obs/loadgen.py":
            # wall-clock pacing of open-loop arrivals is intentional
            return False
        return relpath.startswith(
            ("nomad_tpu/broker/", "nomad_tpu/server/", "nomad_tpu/obs/")
        )

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _Visitor(relpath)
        v.visit(tree)
        return v.findings
