"""NTA015 — device placement goes through the mesh sharding seam.

``utils/backend.py`` owns mesh discovery (``get_mesh``) and array
placement (``shard_put``): it is the ONE site that maps logical axes
("groups", "nodes") to ``NamedSharding`` specs and knows the degenerate
single-device case. A device or scheduler module that calls
``jax.device_put`` directly, or constructs ``NamedSharding`` /
``PartitionSpec`` itself, either pins a tensor to one device (silently
replicating the node axis — the exact full-gather the region-major
layout exists to avoid) or forks the axis-name/divisibility logic so
the two copies drift. Under a 100k-node mesh that is not a style nit:
one bare ``device_put`` of a ``[G, N]`` tensor re-materializes the
whole node axis on every chip per step.

Flagged: any call whose dotted leaf is ``device_put``,
``NamedSharding``, or ``PartitionSpec`` inside ``nomad_tpu/device/``
or ``nomad_tpu/scheduler/``.

Exempt: ``device/cache.py`` — its per-shard incremental refresh IS the
seam's partial-upload half: it must ``device_put`` one shard's slice to
one specific device (``shard_put`` only expresses whole-tensor
layouts). ``utils/backend.py`` itself is out of scope by construction.
"""

from __future__ import annotations

import ast

from ..lint import Finding, Rule, ScopedVisitor, dotted_name

_SCOPES = ("nomad_tpu/device/", "nomad_tpu/scheduler/")
_EXEMPT = ("nomad_tpu/device/cache.py",)

_PLACEMENT_LEAVES = ("device_put", "NamedSharding", "PartitionSpec")


class _PlacementVisitor(ScopedVisitor):
    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in _PLACEMENT_LEAVES:
            self.add(
                "NTA015",
                node,
                f"bare device placement {leaf}(...): route through "
                "utils/backend.py shard_put so node-axis tensors follow "
                "the mesh layout instead of replicating onto every chip",
            )
        self.generic_visit(node)


class ShardingSeamDiscipline(Rule):
    id = "NTA015"
    title = "device placement goes through the mesh sharding seam"

    def applies_to(self, relpath: str) -> bool:
        if relpath in _EXEMPT:
            return False
        return relpath.startswith(_SCOPES)

    def check(self, tree, source, relpath) -> list[Finding]:
        v = _PlacementVisitor(relpath)
        v.visit(tree)
        return v.findings
