"""jit-retrace budget checker.

The placement kernels bucket their dynamic dimensions (node count, victim
count, scan steps) to powers of two precisely so a 10k-node bench batch
costs a handful of XLA compiles, not hundreds. That property regresses
silently: drop one ``static_argnames`` entry or un-bucket one dimension
and every call traces afresh — the suite still passes, the bench just
gets 100× slower. This checker turns the property into an assertion.

Mechanism: the kernels in ``device/score.py`` / ``device/preempt.py`` are
wrapped by ``utils.backend.traced_jit``, which counts one tick per actual
XLA trace and registers each kernel's declared budget. ``budget_window()``
scopes the check: run a representative batch inside the window, and any
tracked kernel whose trace count *within the window* exceeds its budget
raises with the offending counts.

    with retrace.budget_window():
        for _ in range(64):
            kernel.place(ct, asks)     # same shapes -> 1 trace, not 64
"""

from __future__ import annotations

from contextlib import contextmanager

from ..utils import backend


class RetraceBudgetExceeded(AssertionError):
    def __init__(self, offenders: list[tuple[str, int, int]]):
        self.offenders = offenders
        super().__init__(
            "; ".join(
                f"{name}: {count} traces > budget {budget}"
                for name, count, budget in offenders
            )
        )


def counts() -> dict[str, int]:
    """Cumulative trace counts per tracked callable (process lifetime)."""
    return backend.trace_counts()


def budgets() -> dict[str, int]:
    return backend.trace_budgets()


def over_budget(
    window_counts: dict[str, int] | None = None,
) -> list[tuple[str, int, int]]:
    """(name, traces, budget) for every tracked callable past its budget.
    With no argument, checks cumulative process-lifetime counts."""
    current = window_counts if window_counts is not None else counts()
    budget_map = budgets()
    out = [
        (name, current.get(name, 0), budget)
        for name, budget in sorted(budget_map.items())
        if current.get(name, 0) > budget
    ]
    return out


def check(window_counts: dict[str, int] | None = None) -> None:
    offenders = over_budget(window_counts)
    if offenders:
        raise RetraceBudgetExceeded(offenders)


@contextmanager
def budget_window():
    """Scope a budget check to the workload inside the ``with`` block:
    deltas (not cumulative counts) are compared against each declared
    budget, so earlier compiles in the process don't count against it."""
    before = counts()
    yield
    after = counts()
    deltas = {
        name: after.get(name, 0) - before.get(name, 0) for name in after
    }
    check(deltas)


def report() -> dict:
    """CLI/report payload: per-kernel counts vs budgets."""
    current = counts()
    budget_map = budgets()
    return {
        name: {
            "traces": current.get(name, 0),
            "budget": budget_map.get(name),
        }
        for name in sorted(set(current) | set(budget_map))
    }
