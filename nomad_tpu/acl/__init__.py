"""ACL subsystem — policies, compiled ACLs, tokens.

Reference: acl/acl.go (compiled capability checker), acl/policy.go
(HCL policy parse + shorthand expansion), nomad/structs ACLToken/ACLPolicy,
nomad/acl_endpoint.go (bootstrap/policy/token RPCs).
"""

from .acl import ACL, AclCache, MANAGEMENT_ACL, compile_acl
from .policy import (
    POLICY_DENY,
    POLICY_LIST,
    POLICY_READ,
    POLICY_SCALE,
    POLICY_WRITE,
    AclPolicyError,
    NamespacePolicy,
    Policy,
    parse_policy,
)
from .tokens import ACLPolicyRecord, ACLToken

__all__ = [
    "ACL",
    "AclCache",
    "MANAGEMENT_ACL",
    "compile_acl",
    "POLICY_DENY",
    "POLICY_LIST",
    "POLICY_READ",
    "POLICY_SCALE",
    "POLICY_WRITE",
    "AclPolicyError",
    "NamespacePolicy",
    "Policy",
    "parse_policy",
    "ACLPolicyRecord",
    "ACLToken",
]
