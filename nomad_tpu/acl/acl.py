"""Compiled ACL — merge policies into an efficiently-checkable object.

Reference: acl/acl.go. Merge rules: across policies the *maximum*
privilege wins, except ``deny`` which always wins (maxPrivilege,
acl/acl.go:67-85). Namespace/host-volume rules support glob patterns;
on lookup, an exact match wins, otherwise the matching glob with the
smallest character difference ``len(name) - len(pattern)`` is chosen
(findClosestMatchingGlob, acl/acl.go:332-354).
"""

from __future__ import annotations

import fnmatch
import threading
from typing import Iterable, Optional

from .policy import (
    HV_CAP_DENY,
    NS_CAP_DENY,
    POLICY_DENY,
    POLICY_LIST,
    POLICY_READ,
    POLICY_WRITE,
    Policy,
)

def max_privilege(a: str, b: str) -> str:
    """acl/acl.go:67-85 — deny > write > read > list."""
    if POLICY_DENY in (a, b):
        return POLICY_DENY
    if POLICY_WRITE in (a, b):
        return POLICY_WRITE
    if POLICY_READ in (a, b):
        return POLICY_READ
    if POLICY_LIST in (a, b):
        return POLICY_LIST
    return ""


def _glob_match(pattern: str, name: str) -> bool:
    # ryanuber/go-glob semantics: '*' wildcards only (no ? or []).
    return fnmatch.fnmatchcase(
        name, pattern.replace("[", "[[]").replace("?", "[?]")
    )


class ACL:
    """Compiled capability checker (acl/acl.go:42-64)."""

    def __init__(self, management: bool = False):
        self.management = management
        self.namespaces: dict[str, frozenset[str]] = {}
        self.wildcard_namespaces: dict[str, frozenset[str]] = {}
        self.host_volumes: dict[str, frozenset[str]] = {}
        self.wildcard_host_volumes: dict[str, frozenset[str]] = {}
        self.agent = ""
        self.node = ""
        self.operator = ""
        self.quota = ""
        self.plugin = ""

    # -- namespace ---------------------------------------------------------
    def _matching_caps(
        self,
        exact: dict[str, frozenset[str]],
        wild: dict[str, frozenset[str]],
        name: str,
    ) -> Optional[frozenset[str]]:
        caps = exact.get(name)
        if caps is not None:
            return caps
        matches = [
            (len(name) - len(pat), pat, caps)
            for pat, caps in sorted(wild.items())
            if _glob_match(pat, name)
        ]
        if not matches:
            return None
        matches.sort(key=lambda m: m[0])
        return matches[0][2]

    def allow_namespace_operation(self, namespace: str, op: str) -> bool:
        if self.management:
            return True
        caps = self._matching_caps(self.namespaces, self.wildcard_namespaces, namespace)
        if caps is None:
            return False
        return op in caps and NS_CAP_DENY not in caps

    allow_ns_op = allow_namespace_operation

    def allow_namespace(self, namespace: str) -> bool:
        """Any non-deny capability grants namespace visibility."""
        if self.management:
            return True
        caps = self._matching_caps(self.namespaces, self.wildcard_namespaces, namespace)
        if caps is None:
            return False
        return bool(caps) and NS_CAP_DENY not in caps

    # -- host volumes ------------------------------------------------------
    def allow_host_volume_operation(self, volume: str, op: str) -> bool:
        if self.management:
            return True
        caps = self._matching_caps(
            self.host_volumes, self.wildcard_host_volumes, volume
        )
        if caps is None:
            return False
        return op in caps and HV_CAP_DENY not in caps

    # -- coarse scopes -----------------------------------------------------
    def _coarse(self, level: str, need_write: bool) -> bool:
        if self.management:
            return True
        if level == POLICY_DENY:
            return False
        if need_write:
            return level == POLICY_WRITE
        return level in (POLICY_READ, POLICY_WRITE, POLICY_LIST)

    def allow_agent_read(self) -> bool:
        return self._coarse(self.agent, False)

    def allow_agent_write(self) -> bool:
        return self._coarse(self.agent, True)

    def allow_node_read(self) -> bool:
        return self._coarse(self.node, False)

    def allow_node_write(self) -> bool:
        return self._coarse(self.node, True)

    def allow_operator_read(self) -> bool:
        return self._coarse(self.operator, False)

    def allow_operator_write(self) -> bool:
        return self._coarse(self.operator, True)

    def allow_quota_read(self) -> bool:
        return self._coarse(self.quota, False)

    def allow_quota_write(self) -> bool:
        return self._coarse(self.quota, True)

    def allow_plugin_read(self) -> bool:
        return self._coarse(self.plugin, False)

    def allow_plugin_list(self) -> bool:
        if self.management:
            return True
        return self.plugin not in ("", POLICY_DENY)

    def is_management(self) -> bool:
        return self.management


def compile_acl(policies: Iterable[Policy]) -> ACL:
    """NewACL (acl/acl.go:88-177): union capabilities per namespace/volume,
    maxPrivilege for coarse scopes; deny capability sticks."""
    acl = ACL(management=False)
    ns_caps: dict[str, set[str]] = {}
    hv_caps: dict[str, set[str]] = {}
    for p in policies:
        for ns in p.namespaces:
            ns_caps.setdefault(ns.name, set()).update(ns.capabilities)
        for hv in p.host_volumes:
            hv_caps.setdefault(hv.name, set()).update(hv.capabilities)
        acl.agent = max_privilege(acl.agent, p.agent)
        acl.node = max_privilege(acl.node, p.node)
        acl.operator = max_privilege(acl.operator, p.operator)
        acl.quota = max_privilege(acl.quota, p.quota)
        acl.plugin = max_privilege(acl.plugin, p.plugin)
    for name, caps in ns_caps.items():
        target = acl.wildcard_namespaces if "*" in name else acl.namespaces
        target[name] = frozenset(caps)
    for name, caps in hv_caps.items():
        target = acl.wildcard_host_volumes if "*" in name else acl.host_volumes
        target[name] = frozenset(caps)
    return acl


MANAGEMENT_ACL = ACL(management=True)


class AclCache:
    """Bounded cache of compiled ACLs keyed by the contributing policy
    names + modify indexes (the reference caches by policy content hash,
    nomad/acl.go resolveTokenACL)."""

    def __init__(self, maxsize: int = 512):
        self._cache: dict[tuple, ACL] = {}
        self._lock = threading.Lock()
        self._maxsize = maxsize

    def get_or_compile(self, key: tuple, policies_fn) -> ACL:
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                return hit
        acl = compile_acl(policies_fn())
        with self._lock:
            if len(self._cache) >= self._maxsize:
                self._cache.clear()
            self._cache[key] = acl
        return acl
