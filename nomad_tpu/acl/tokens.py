"""ACL token + stored-policy records.

Reference: structs.ACLToken / structs.ACLPolicy
(nomad/structs/structs.go ACL section) and the bootstrap/management
semantics of nomad/acl_endpoint.go.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field

TOKEN_TYPE_CLIENT = "client"
TOKEN_TYPE_MANAGEMENT = "management"

ANONYMOUS_TOKEN_NAME = "Anonymous Token"
ANONYMOUS_POLICY_NAME = "anonymous"


@dataclass
class ACLPolicyRecord:
    """A named, stored policy document (structs.ACLPolicy)."""

    name: str
    description: str = ""
    rules: str = ""
    create_index: int = 0
    modify_index: int = 0

    def to_api(self) -> dict:
        return {
            "Name": self.name,
            "Description": self.description,
            "Rules": self.rules,
            "CreateIndex": self.create_index,
            "ModifyIndex": self.modify_index,
        }


@dataclass
class ACLToken:
    """structs.ACLToken: accessor (public id) + secret (bearer value)."""

    accessor_id: str = field(default_factory=lambda: str(uuid.uuid4()))
    secret_id: str = field(default_factory=lambda: str(uuid.uuid4()))
    name: str = ""
    type: str = TOKEN_TYPE_CLIENT
    policies: list[str] = field(default_factory=list)
    global_: bool = False
    create_time: float = field(default_factory=time.time)
    create_index: int = 0
    modify_index: int = 0

    def is_management(self) -> bool:
        return self.type == TOKEN_TYPE_MANAGEMENT

    def validate(self) -> list[str]:
        errs = []
        if len(self.name) > 256:
            errs.append("token name too long")
        if self.type not in (TOKEN_TYPE_CLIENT, TOKEN_TYPE_MANAGEMENT):
            errs.append("token type must be client or management")
        if self.type == TOKEN_TYPE_CLIENT and not self.policies:
            errs.append("client token missing policies")
        if self.type == TOKEN_TYPE_MANAGEMENT and self.policies:
            errs.append("management token cannot be associated with policies")
        return errs

    def to_api(self, redact_secret: bool = False) -> dict:
        return {
            "AccessorID": self.accessor_id,
            "SecretID": "" if redact_secret else self.secret_id,
            "Name": self.name,
            "Type": self.type,
            "Policies": list(self.policies),
            "Global": self.global_,
            "CreateTime": self.create_time,
            "CreateIndex": self.create_index,
            "ModifyIndex": self.modify_index,
        }
