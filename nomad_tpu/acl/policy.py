"""ACL policy parsing — HCL rules → Policy with expanded capabilities.

Reference: acl/policy.go. Policies are HCL documents of the shape:

    namespace "default" {
      policy       = "read"
      capabilities = ["submit-job"]
    }
    host_volume "prod-*" { policy = "write" }
    node     { policy = "write" }
    agent    { policy = "read" }
    operator { policy = "write" }
    quota    { policy = "read" }
    plugin   { policy = "list" }

Coarse ``policy`` levels expand to fine-grained capability lists
(acl/policy.go:166-232); ``deny`` always wins on merge.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

from ..utils import hcl

# Coarse policy dispositions (acl/policy.go:14-19)
POLICY_DENY = "deny"
POLICY_READ = "read"
POLICY_LIST = "list"
POLICY_WRITE = "write"
POLICY_SCALE = "scale"

# Namespace capabilities (acl/policy.go:27-48)
NS_CAP_DENY = "deny"
NS_CAP_LIST_JOBS = "list-jobs"
NS_CAP_READ_JOB = "read-job"
NS_CAP_SUBMIT_JOB = "submit-job"
NS_CAP_DISPATCH_JOB = "dispatch-job"
NS_CAP_READ_LOGS = "read-logs"
NS_CAP_READ_FS = "read-fs"
NS_CAP_ALLOC_EXEC = "alloc-exec"
NS_CAP_ALLOC_NODE_EXEC = "alloc-node-exec"
NS_CAP_ALLOC_LIFECYCLE = "alloc-lifecycle"
NS_CAP_CSI_REGISTER_PLUGIN = "csi-register-plugin"
NS_CAP_CSI_WRITE_VOLUME = "csi-write-volume"
NS_CAP_CSI_READ_VOLUME = "csi-read-volume"
NS_CAP_CSI_LIST_VOLUME = "csi-list-volume"
NS_CAP_CSI_MOUNT_VOLUME = "csi-mount-volume"
NS_CAP_LIST_SCALING_POLICIES = "list-scaling-policies"
NS_CAP_READ_SCALING_POLICY = "read-scaling-policy"
NS_CAP_READ_JOB_SCALING = "read-job-scaling"
NS_CAP_SCALE_JOB = "scale-job"
NS_CAP_SUBMIT_RECOMMENDATION = "submit-recommendation"

_VALID_NS_CAPS = {
    NS_CAP_DENY,
    NS_CAP_LIST_JOBS,
    NS_CAP_READ_JOB,
    NS_CAP_SUBMIT_JOB,
    NS_CAP_DISPATCH_JOB,
    NS_CAP_READ_LOGS,
    NS_CAP_READ_FS,
    NS_CAP_ALLOC_EXEC,
    NS_CAP_ALLOC_NODE_EXEC,
    NS_CAP_ALLOC_LIFECYCLE,
    NS_CAP_CSI_REGISTER_PLUGIN,
    NS_CAP_CSI_WRITE_VOLUME,
    NS_CAP_CSI_READ_VOLUME,
    NS_CAP_CSI_LIST_VOLUME,
    NS_CAP_CSI_MOUNT_VOLUME,
    NS_CAP_LIST_SCALING_POLICIES,
    NS_CAP_READ_SCALING_POLICY,
    NS_CAP_READ_JOB_SCALING,
    NS_CAP_SCALE_JOB,
    NS_CAP_SUBMIT_RECOMMENDATION,
}

# Host-volume capabilities (acl/policy.go:55-64)
HV_CAP_DENY = "deny"
HV_CAP_MOUNT_READONLY = "mount-readonly"
HV_CAP_MOUNT_READWRITE = "mount-readwrite"

_VALID_HV_CAPS = {HV_CAP_DENY, HV_CAP_MOUNT_READONLY, HV_CAP_MOUNT_READWRITE}

_VALID_NAME = re.compile(r"^[a-zA-Z0-9-*]{1,128}$")


class AclPolicyError(Exception):
    pass


@dataclass
class NamespacePolicy:
    name: str
    policy: str = ""
    capabilities: list[str] = field(default_factory=list)


@dataclass
class HostVolumePolicy:
    name: str
    policy: str = ""
    capabilities: list[str] = field(default_factory=list)


@dataclass
class Policy:
    namespaces: list[NamespacePolicy] = field(default_factory=list)
    host_volumes: list[HostVolumePolicy] = field(default_factory=list)
    agent: str = ""
    node: str = ""
    operator: str = ""
    quota: str = ""
    plugin: str = ""
    raw: str = ""

    def is_empty(self) -> bool:
        return (
            not self.namespaces
            and not self.host_volumes
            and not self.agent
            and not self.node
            and not self.operator
            and not self.quota
            and not self.plugin
        )


def expand_namespace_policy(policy: str) -> list[str]:
    """acl/policy.go:166-211."""
    read = [
        NS_CAP_LIST_JOBS,
        NS_CAP_READ_JOB,
        NS_CAP_CSI_LIST_VOLUME,
        NS_CAP_CSI_READ_VOLUME,
        NS_CAP_READ_JOB_SCALING,
        NS_CAP_LIST_SCALING_POLICIES,
        NS_CAP_READ_SCALING_POLICY,
    ]
    write = read + [
        NS_CAP_SCALE_JOB,
        NS_CAP_SUBMIT_JOB,
        NS_CAP_DISPATCH_JOB,
        NS_CAP_READ_LOGS,
        NS_CAP_READ_FS,
        NS_CAP_ALLOC_EXEC,
        NS_CAP_ALLOC_LIFECYCLE,
        NS_CAP_CSI_MOUNT_VOLUME,
        NS_CAP_CSI_WRITE_VOLUME,
        NS_CAP_SUBMIT_RECOMMENDATION,
    ]
    if policy == POLICY_DENY:
        return [NS_CAP_DENY]
    if policy == POLICY_READ:
        return read
    if policy == POLICY_WRITE:
        return write
    if policy == POLICY_SCALE:
        return [
            NS_CAP_LIST_SCALING_POLICIES,
            NS_CAP_READ_SCALING_POLICY,
            NS_CAP_READ_JOB_SCALING,
            NS_CAP_SCALE_JOB,
        ]
    return []


def expand_host_volume_policy(policy: str) -> list[str]:
    """acl/policy.go:221-232."""
    if policy == POLICY_DENY:
        return [HV_CAP_DENY]
    if policy == POLICY_READ:
        return [HV_CAP_MOUNT_READONLY]
    if policy == POLICY_WRITE:
        return [HV_CAP_MOUNT_READONLY, HV_CAP_MOUNT_READWRITE]
    return []


def _is_policy_valid(p: str) -> bool:
    return p in (POLICY_DENY, POLICY_READ, POLICY_WRITE, POLICY_SCALE)


def _coarse_only(p: str) -> bool:
    """agent/node/operator/quota/plugin accept deny|read|write (plugin also
    list) — acl/policy.go isPolicyValid + isPluginPolicyValid."""
    return p in (POLICY_DENY, POLICY_READ, POLICY_WRITE)


def _block_policy(block: Optional[hcl.Block], what: str, allow_list=False) -> str:
    if block is None:
        return ""
    ctx = hcl.EvalContext()
    attr = block.body.attrs.get("policy")
    if attr is None:
        return ""
    p = attr.expr(ctx)
    valid = _coarse_only(p) or (allow_list and p == POLICY_LIST)
    if not valid:
        raise AclPolicyError(f"Invalid {what} policy: {p!r}")
    return p


def parse_policy(rules: str) -> Policy:
    """Parse + validate + expand an HCL policy document (acl/policy.go:237)."""
    p = Policy(raw=rules)
    if not rules.strip():
        return p
    try:
        body = hcl.parse(rules)
    except hcl.HCLError as e:
        raise AclPolicyError(f"Failed to parse ACL Policy: {e}") from e
    ctx = hcl.EvalContext()

    for b in body.blocks_of("namespace"):
        if len(b.labels) != 1:
            raise AclPolicyError("namespace block requires exactly one label")
        ns = NamespacePolicy(name=b.labels[0])
        if "policy" in b.body.attrs:
            ns.policy = b.body.attrs["policy"].expr(ctx)
        if "capabilities" in b.body.attrs:
            ns.capabilities = list(b.body.attrs["capabilities"].expr(ctx))
        if not _VALID_NAME.match(ns.name):
            raise AclPolicyError(f"Invalid namespace name: {ns.name!r}")
        if ns.policy and not _is_policy_valid(ns.policy):
            raise AclPolicyError(f"Invalid namespace policy: {ns.policy!r}")
        for cap in ns.capabilities:
            if cap not in _VALID_NS_CAPS:
                raise AclPolicyError(f"Invalid namespace capability: {cap!r}")
        if ns.policy:
            ns.capabilities = ns.capabilities + expand_namespace_policy(ns.policy)
        p.namespaces.append(ns)

    for b in body.blocks_of("host_volume"):
        if len(b.labels) != 1:
            raise AclPolicyError("host_volume block requires exactly one label")
        hv = HostVolumePolicy(name=b.labels[0])
        if "policy" in b.body.attrs:
            hv.policy = b.body.attrs["policy"].expr(ctx)
        if "capabilities" in b.body.attrs:
            hv.capabilities = list(b.body.attrs["capabilities"].expr(ctx))
        if not _VALID_NAME.match(hv.name):
            raise AclPolicyError(f"Invalid host volume name: {hv.name!r}")
        if hv.policy and not _is_policy_valid(hv.policy):
            raise AclPolicyError(f"Invalid host volume policy: {hv.policy!r}")
        for cap in hv.capabilities:
            if cap not in _VALID_HV_CAPS:
                raise AclPolicyError(f"Invalid host volume capability: {cap!r}")
        if hv.policy:
            hv.capabilities = hv.capabilities + expand_host_volume_policy(hv.policy)
        p.host_volumes.append(hv)

    p.agent = _block_policy(body.first("agent"), "agent")
    p.node = _block_policy(body.first("node"), "node")
    p.operator = _block_policy(body.first("operator"), "operator")
    p.quota = _block_policy(body.first("quota"), "quota")
    p.plugin = _block_policy(body.first("plugin"), "plugin", allow_list=True)

    if p.is_empty():
        raise AclPolicyError(f"Invalid policy: {rules!r}")
    return p
