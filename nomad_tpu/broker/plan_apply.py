"""Plan applier — the leader's serialization point.

Reference: nomad/plan_apply.go. The scheduler's plan was computed against a
possibly-stale snapshot, so before commit the applier re-verifies, node by
node, that every proposed placement still fits (evaluateNodePlan :638-689
re-runs AllocsFit against the leader's current state), partially commits
what fits, and hands back ``refresh_index`` so the worker retries the
remainder on fresher state (:576-594). Port assignment happens here too —
the scheduler scored with bandwidth/port-count aggregates only (the
guess-then-verify split, SURVEY.md §7 "hard parts").

The reference parallelizes per-node verification over an EvaluatePool of
NumCPU/2 goroutines (plan_apply_pool.go:18-40); here the same check is a
vectorized host pass (and the touched-node count per plan is small).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from ..chaos.plane import active_plane, chaos_site, note_committed
from ..obs.trace import global_tracer as tracer
from ..structs import (
    Allocation,
    MergedPlan,
    NetworkIndex,
    Plan,
    PlanResult,
    allocs_fit,
)
from ..structs.resources import node_comparable_capacity
from ..utils.metrics import count_swallowed, global_metrics as metrics


class PlanTokenMismatch(Exception):
    """The plan's broker token is no longer the eval's outstanding token:
    the unack deadline redelivered the eval mid-commit and another worker
    owns it now. The stale submitter must drop its plan, not retry —
    committing both copies would place the job twice (a surplus no
    remaining eval reconciles). Mirrors the reference's token validation
    on plan submission (plan_endpoint.go / OutstandingReset)."""


def evaluate_node_plan(snapshot, plan: Plan, node_id: str) -> tuple[bool, str]:
    """Can this node absorb the plan's changes for it?
    (plan_apply.go:638-689). Returns (fits, reason)."""
    node = snapshot.node_by_id(node_id)
    if node is None:
        return False, "node does not exist"
    if node.terminal_status():
        return False, "node is not allowed to receive allocations"

    existing = snapshot.allocs_by_node(node_id)
    removed = {
        a.id for a in plan.node_update.get(node_id, ())
    } | {a.id for a in plan.node_preemptions.get(node_id, ())}
    proposed = [a for a in existing if a.id not in removed]
    # updated allocs replace their stored copy
    new_allocs = plan.node_allocation.get(node_id, ())
    new_ids = {a.id for a in new_allocs}
    proposed = [a for a in proposed if a.id not in new_ids]
    proposed.extend(new_allocs)

    ok, dim, _used = allocs_fit(node, proposed, check_devices=True)
    if not ok:
        return False, f"resources exhausted: {dim}"

    # port collision re-check — skipped entirely when nothing on the
    # node carries a network (the common case; building a NetworkIndex
    # per touched node was a measurable slice of the applier's verify)
    if any(getattr(a, "allocated_networks", None) for a in proposed):
        return _node_ports_ok(node, proposed, new_allocs)
    return True, ""


def _node_ports_ok(node, proposed, new_allocs) -> tuple[bool, str]:
    """Port-collision re-check for one node: existing reservations index
    first, then each new alloc's ports against it (evaluateNodePlan's
    NetworkIndex walk)."""
    new_ids = {a.id for a in new_allocs}
    idx = NetworkIndex(node)
    if not idx.add_allocs(a for a in proposed if a.id not in new_ids):
        return False, "port collision in existing allocations"
    for a in new_allocs:
        for net in a.allocated_networks:
            for p in net.reserved_ports + net.dynamic_ports:
                if p.value in idx.used_ports:
                    return False, f"port {p.value} already in use"
        for net in a.allocated_networks:
            idx.add_reserved_network(net)
    return True, ""


def _csi_claims_ok(snapshot, allocs, claimed: dict) -> bool:
    """Optimistic CSI re-verify: would every placed alloc's volume claim
    still succeed against current claim state? ``claimed`` accumulates
    in-plan claims (readers and writers) so two placements in one plan
    can't jointly exceed a volume's access mode — the claim analog of
    evaluateNodePlan's AllocsFit re-check.

    Claims are staged into a local copy and merged into ``claimed`` only
    when the whole node passes; a rejected node's allocs never commit, so
    leaking their claims would spuriously block later nodes in the plan."""
    from ..structs.volumes import (
        ACCESS_MODE_MULTI_NODE_MULTI_WRITER,
        ACCESS_MODE_SINGLE_NODE_READER,
        ACCESS_MODE_SINGLE_NODE_WRITER,
    )

    staged = dict(claimed)
    for a in allocs:
        if a.job is None or a.client_status != "pending":
            continue
        tg = a.job.lookup_task_group(a.task_group)
        if tg is None or not getattr(tg, "volumes", None):
            continue
        for req in tg.volumes.values():
            if req.type != "csi":
                continue
            vid = req.source
            if req.per_alloc:
                per = f"{req.source}[{a.index()}]"
                if snapshot.csi_volume_by_id(per) is not None:
                    vid = per
            vol = snapshot.csi_volume_by_id(vid)
            if vol is None:
                return False
            if not vol.claimable(req.read_only):
                return False
            readers, writers = staged.get(vid, (0, 0))
            single_node = vol.access_mode in (
                ACCESS_MODE_SINGLE_NODE_READER,
                ACCESS_MODE_SINGLE_NODE_WRITER,
            )
            if req.read_only:
                # single-node modes admit one claimant total
                if single_node and (
                    readers + writers + len(vol.read_claims)
                    + len(vol.write_claims)
                ) >= 1:
                    return False
                staged[vid] = (readers + 1, writers)
            else:
                if vol.access_mode != ACCESS_MODE_MULTI_NODE_MULTI_WRITER and (
                    writers + len(vol.write_claims) >= 1
                    or (single_node and readers + len(vol.read_claims) >= 1)
                ):
                    return False
                staged[vid] = (readers, writers + 1)
    claimed.update(staged)
    return True


def evaluate_plan(snapshot, plan: Plan) -> PlanResult:
    """Per-node verify + partial commit (plan_apply.go:400-596): nodes that
    fail verification are dropped from the result; when anything is
    dropped, refresh_index tells the worker to retry on fresher state."""
    result = PlanResult(alloc_index=0)
    rejected = []
    touched = set(plan.node_allocation) | set(plan.node_update) | set(
        plan.node_preemptions
    )
    claimed: dict[str, tuple[int, int]] = {}  # vid → (readers, writers)
    for node_id in sorted(touched):
        has_new = node_id in plan.node_allocation
        if has_new:
            ok, reason = evaluate_node_plan(snapshot, plan, node_id)
            if ok and not _csi_claims_ok(
                snapshot, plan.node_allocation[node_id], claimed
            ):
                ok = False
            if not ok:
                rejected.append(node_id)
                # stops/preemptions still commit (they only free capacity)
                if node_id in plan.node_update:
                    result.node_update[node_id] = list(plan.node_update[node_id])
                continue
        if node_id in plan.node_update:
            result.node_update[node_id] = list(plan.node_update[node_id])
        if node_id in plan.node_preemptions:
            result.node_preemptions[node_id] = list(
                plan.node_preemptions[node_id]
            )
        if has_new:
            result.node_allocation[node_id] = list(plan.node_allocation[node_id])

    result.rejected_nodes = rejected
    if rejected:
        result.refresh_index = getattr(snapshot, "latest_index", 0) or getattr(
            snapshot, "index", 0
        )
    result.deployment = plan.deployment
    result.deployment_updates = list(plan.deployment_updates)
    return result


def _merged_touched_nodes(plans) -> dict[str, list[int]]:
    """node id → ordered member ordinals touching it (a member appears
    once even when it touches the node in several buckets)."""
    touched: dict[str, list[int]] = {}
    for i, mp in enumerate(plans):
        for bucket in (mp.node_allocation, mp.node_update, mp.node_preemptions):
            for node_id in bucket:
                members = touched.setdefault(node_id, [])
                if not members or members[-1] != i:
                    members.append(i)
    return touched


def _fast_path_slack(snapshot, node_id, member_plans):
    """Vectorized-verify candidacy for one node: when every touching
    member only ADDS networkless, deviceless, claim-free allocations, the
    whole union check reduces to ``free - sum(asks) >= 0`` per dimension.
    Returns that slack vector, or None to route the node to the exact
    per-member walk (which reproduces evaluate_node_plan bit for bit)."""
    node = snapshot.node_by_id(node_id)
    if node is None or node.terminal_status():
        return None
    new_allocs = []
    for mp in member_plans:
        if node_id in mp.node_update or node_id in mp.node_preemptions:
            return None
        new_allocs.extend(mp.node_allocation.get(node_id, ()))
    existing = snapshot.allocs_by_node(node_id)
    existing_ids = {a.id for a in existing}
    for a in new_allocs:
        if (
            a.id in existing_ids  # in-place update: replacement math
            or a.allocated_networks  # needs the NetworkIndex re-check
            or a.allocated_devices  # needs device-pool accounting
            or a.job is not None  # un-normalized: CSI/device asks possible
        ):
            return None
    free = node_comparable_capacity(node).to_vector()
    for a in existing:
        if a.terminal_status():
            continue
        if a.allocated_networks or a.allocated_devices or a.job is not None:
            return None
        free = free - a.comparable_resources().to_vector()
    for a in new_allocs:
        free = free - a.comparable_resources().to_vector()
    return free


def _evaluate_node_members(
    snapshot, node_id: str, ordered, results, claimed
) -> None:
    """Exact member-order admission for one node shared by several member
    plans: each member is checked against existing allocs PLUS everything
    earlier members already got admitted, so two members of one merged
    commit can never jointly overcommit a node. A failing member gets the
    node in its ``rejected_nodes`` (stops still commit — they only free
    capacity); siblings are unaffected. ``ordered`` is [(ordinal,
    member_plan)] in batch order; ``results`` is indexed by ordinal."""
    node = snapshot.node_by_id(node_id)
    node_ok = node is not None and not node.terminal_status()
    base = list(snapshot.allocs_by_node(node_id)) if node_ok else []
    for ordinal, mp in ordered:
        result = results[ordinal]
        stops = mp.node_update.get(node_id, ())
        preempts = mp.node_preemptions.get(node_id, ())
        new_allocs = mp.node_allocation.get(node_id, ())
        if not new_allocs:
            # freeing-only member: always commits (matches evaluate_plan's
            # no-placement branch)
            if stops:
                result.node_update[node_id] = list(stops)
            if preempts:
                result.node_preemptions[node_id] = list(preempts)
            removed = {a.id for a in stops} | {a.id for a in preempts}
            if removed:
                base = [a for a in base if a.id not in removed]
            continue
        ok = node_ok
        proposed: list = []
        if ok:
            removed = {a.id for a in stops} | {a.id for a in preempts}
            new_ids = {a.id for a in new_allocs}
            proposed = [
                a for a in base
                if a.id not in removed and a.id not in new_ids
            ]
            proposed.extend(new_allocs)
            ok, _dim, _used = allocs_fit(node, proposed, check_devices=True)
        if ok and any(
            getattr(a, "allocated_networks", None) for a in proposed
        ):
            ok, _reason = _node_ports_ok(node, proposed, new_allocs)
        if ok and not _csi_claims_ok(snapshot, new_allocs, claimed):
            ok = False
        if not ok:
            result.rejected_nodes.append(node_id)
            # stops still commit — the single-plan partial-commit rule
            if stops:
                result.node_update[node_id] = list(stops)
                stop_ids = {a.id for a in stops}
                base = [a for a in base if a.id not in stop_ids]
            continue
        if stops:
            result.node_update[node_id] = list(stops)
        if preempts:
            result.node_preemptions[node_id] = list(preempts)
        result.node_allocation[node_id] = list(new_allocs)
        base = proposed


def evaluate_merged_plan(snapshot, plans) -> list[PlanResult]:
    """Verify a whole batched pass's member plans in ONE union-of-nodes
    walk instead of N sequential per-plan walks, committing partially per
    MEMBER: a node whose union of asks still fits admits every member in
    one vectorized check; a node that fails (or needs ports / devices /
    CSI / eviction math) drops to the exact member-order walk, where only
    the members that no longer fit are rejected. Each rejected member
    gets its own ``refresh_index``; siblings commit untouched."""
    results = [PlanResult(alloc_index=0) for _ in plans]
    touched = _merged_touched_nodes(plans)
    slow_nodes: list[str] = []
    fast_ids: list[str] = []
    fast_rows: list = []
    for node_id in sorted(touched):
        slack = _fast_path_slack(
            snapshot, node_id, [plans[i] for i in touched[node_id]]
        )
        if slack is None:
            slow_nodes.append(node_id)
        else:
            fast_ids.append(node_id)
            fast_rows.append(slack)
    if fast_ids:
        fits = (np.stack(fast_rows) >= 0).all(axis=1)
        for node_id, node_fits in zip(fast_ids, fits):
            if node_fits:
                for i in touched[node_id]:
                    allocs = plans[i].node_allocation.get(node_id)
                    if allocs:
                        results[i].node_allocation[node_id] = list(allocs)
            else:
                slow_nodes.append(node_id)
    claimed: dict[str, tuple[int, int]] = {}  # vid → (readers, writers)
    for node_id in sorted(slow_nodes):
        _evaluate_node_members(
            snapshot,
            node_id,
            [(i, plans[i]) for i in touched[node_id]],
            results,
            claimed,
        )
    refresh = getattr(snapshot, "latest_index", 0) or getattr(
        snapshot, "index", 0
    )
    for i, mp in enumerate(plans):
        res = results[i]
        res.deployment = mp.deployment
        res.deployment_updates = list(mp.deployment_updates)
        if res.rejected_nodes:
            res.refresh_index = refresh
    return results


def preemption_evals(store, result: PlanResult) -> list:
    """One follow-up evaluation per job that lost allocations to
    preemption, so victim jobs replace their capacity (the reference
    applier creates PreemptionEvals in applyPlan, nomad/plan_apply.go)."""
    from ..structs import Evaluation
    from ..structs.evaluation import EVAL_STATUS_PENDING, TRIGGER_PREEMPTION

    jobs: dict[tuple[str, str], object] = {}
    for allocs in result.node_preemptions.values():
        for a in allocs:
            jobs.setdefault((a.namespace, a.job_id), a)
    evals = []
    for (ns, job_id), _a in jobs.items():
        job = store.job_by_id(ns, job_id)
        if job is None or job.stopped():
            continue
        evals.append(
            Evaluation(
                namespace=ns,
                priority=job.priority,
                type=job.type,
                triggered_by=TRIGGER_PREEMPTION,
                job_id=job_id,
                status=EVAL_STATUS_PENDING,
            )
        )
    return evals


class PlanApplier:
    """Serialized apply loop state: evaluate against live store, commit
    through the raft seam (applyPlan → raftApply(ApplyPlanResultsRequest),
    plan_apply.go:204-318). One instance per leader. ``commit`` submits the
    PLAN_RESULT FSM message and returns the committed index; when absent
    (bare Harness tests) the result is applied to the store directly.
    ``on_evals_created`` (if set) receives preemption follow-up evals for
    broker enqueue."""

    def __init__(self, store, on_evals_created=None, commit=None,
                 commit_merged=None, lanes=None, token_check=None):
        self.store = store
        self.on_evals_created = on_evals_created
        self.commit = commit
        self.commit_merged = commit_merged
        # LaneMap when deterministic lane ownership is active: merged
        # plans then carry an owner_worker and the applier ASSERTS lane
        # disjointness instead of discovering conflicts optimistically
        self.lanes = lanes
        # callable(eval_id, token) -> bool: is the token still the
        # eval's CURRENT outstanding broker token? The reference's
        # submission guard (plan_endpoint.go token validation): once the
        # unack deadline redelivers an eval, the original worker's plan
        # must not commit — two workers racing one redelivered eval
        # would otherwise both place it (committed surplus with no eval
        # left to reconcile it). None (or an empty plan token) skips the
        # check — direct callers and tests submit outside the broker.
        self.token_check = token_check
        self._lock = threading.Lock()

    def _token_stale(self, plan) -> bool:
        token = getattr(plan, "eval_token", "")
        if not token or self.token_check is None:
            return False
        if self.token_check(plan.eval_id, token):
            return False
        metrics.incr("nomad.plan.stale_token_rejects")
        return True

    def _check_lane_ownership(self, mplan: MergedPlan) -> None:
        """The structural assertion lane mode buys us: every node a
        merged plan places on must belong to the committing worker's
        lanes or be covered by a confirmed cross-lane claim attached to
        the plan. Anything else means a worker escaped the lane
        contract — count it as a lane conflict (invariant law 9 pins the
        counter at zero) and log through the swallow ledger so the
        flight recorder sees it; the member still verifies/commits
        normally (the applier stays the capacity authority)."""
        claimed = {
            n for c in mplan.claims
            if getattr(c, "confirmed", False)
            for n in c.node_ids()
        }
        for plan in mplan.plans:
            for node_id in plan.node_allocation:
                owner = self.lanes.owner_of_node(node_id)
                if owner != mplan.owner_worker and node_id not in claimed:
                    metrics.incr("nomad.plan.lane_conflicts")
                    count_swallowed(
                        "lanes",
                        AssertionError(
                            f"merged plan from worker {mplan.owner_worker} "
                            f"touches node {node_id} (owner w{owner}) "
                            "without a confirmed cross-lane claim"
                        ),
                    )

    def _check_lane_rejections(self, mplan, results) -> None:
        """Post-verify: a rejected node the committing worker does NOT
        own means a cross-lane race slipped the claim protocol (a
        confirmed claim re-checked capacity on a fresh snapshot, so it
        cannot be bounced for fit). Own-lane rejections stay ordinary
        optimistic staleness — solo retry, not a lane conflict."""
        for res in results:
            for node_id in res.rejected_nodes:
                if self.lanes.owner_of_node(node_id) != mplan.owner_worker:
                    metrics.incr("nomad.plan.lane_conflicts")
                    count_swallowed(
                        "lanes",
                        AssertionError(
                            f"cross-lane rejection on {node_id} for "
                            f"worker {mplan.owner_worker}"
                        ),
                    )

    def apply(self, plan: Plan) -> PlanResult:
        with self._lock, tracer.span(
            "plan_apply", timer="nomad.plan.apply"
        ) as sp:
            if self._token_stale(plan):
                raise PlanTokenMismatch(
                    f"eval {plan.eval_id}: broker token rotated before "
                    "apply (redelivered to another worker)"
                )
            with tracer.span(
                "plan_apply.evaluate", timer="nomad.plan.evaluate"
            ):
                chaos_site("plan_apply.verify")
                result = evaluate_plan(self.store, plan)
            if sp is not None:
                sp.tags["rejected_nodes"] = len(result.rejected_nodes)
            if not result.is_no_op() or result.deployment is not None:
                evals = (
                    preemption_evals(self.store, result)
                    if result.node_preemptions else []
                )
                # ledger wants fresh placements only: an id already in
                # the store is an in-place update, not a placement
                fresh = (
                    [
                        a.id
                        for allocs in result.node_allocation.values()
                        for a in allocs
                        if self.store.alloc_by_id(a.id) is None
                    ]
                    if active_plane() is not None
                    else ()
                )
                with tracer.span("plan_apply.commit"):
                    # before the commit executes: a raise here aborts
                    # cleanly (nothing lands, the waiter sees the error)
                    chaos_site("plan_apply.commit")
                    if self.commit is not None:
                        index = self.commit(result, plan.eval_id, evals)
                    else:
                        index = self.store.latest_index + 1
                        self.store.upsert_plan_results(
                            index, result, plan.eval_id
                        )
                        if evals:
                            self.store.upsert_evals(
                                self.store.latest_index + 1, evals
                            )
                note_committed(fresh)
                # commit-train accounting: one FSM apply, one plan landed
                metrics.incr("nomad.plan.commits")
                metrics.incr("nomad.plan.committed_plans")
                result.alloc_index = index
                if evals and self.on_evals_created is not None:
                    # re-read post-commit: a consensus FSM applies COPIES,
                    # so the submitted objects lack committed modify_index
                    self.on_evals_created([
                        self.store.eval_by_id(e.id) or e for e in evals
                    ])
            if result.rejected_nodes:
                result.refresh_index = self.store.latest_index
            return result

    def apply_merged(self, mplan: MergedPlan) -> tuple[list[PlanResult], dict]:
        """Verify + commit one merged batch under the serialized applier
        lock: one union verify pass, one FSM/Raft entry, one store index
        bump — per-member attribution preserved in the returned results.
        Returns (results, phase timings in seconds); the apply loop
        records the timings as shared spans into every member's trace."""
        t_apply = time.perf_counter()
        with self._lock:
            lane_mode = self.lanes is not None and mplan.owner_worker >= 0
            if lane_mode:
                self._check_lane_ownership(mplan)
            t0 = time.perf_counter()
            chaos_site("plan_apply.verify")
            # stale-token members are excluded BEFORE the union verify:
            # a redelivered eval's duplicate placements must neither
            # commit nor consume capacity that would bounce a live
            # sibling. Their result slot is an empty, flagged no-op so
            # per-member attribution stays aligned with mplan.plans.
            stale = [self._token_stale(p) for p in mplan.plans]
            if any(stale):
                live_idx = [i for i, s in enumerate(stale) if not s]
                live = evaluate_merged_plan(
                    self.store, [mplan.plans[i] for i in live_idx]
                )
                results = [
                    PlanResult(token_stale=True) for _ in mplan.plans
                ]
                for i, res in zip(live_idx, live):
                    results[i] = res
            else:
                results = evaluate_merged_plan(self.store, mplan.plans)
            if lane_mode:
                self._check_lane_rejections(mplan, results)
            evaluate_s = time.perf_counter() - t0
            metrics.measure("nomad.plan.evaluate", evaluate_s)
            # merged-only sample so the bench can report the batched
            # verify latency separately from single-plan evaluates
            metrics.measure("nomad.plan.verify_batch", evaluate_s)
            commit_members = [
                (mp.eval_id, res)
                for mp, res in zip(mplan.plans, results)
                if not res.is_no_op() or res.deployment is not None
            ]
            evals: list = []
            for _eid, res in commit_members:
                if res.node_preemptions:
                    evals.extend(preemption_evals(self.store, res))
            t0 = time.perf_counter()
            if commit_members:
                fresh = (
                    [
                        a.id
                        for _eid, res in commit_members
                        for allocs in res.node_allocation.values()
                        for a in allocs
                        if self.store.alloc_by_id(a.id) is None
                    ]
                    if active_plane() is not None
                    else ()
                )
                chaos_site("plan_apply.commit")
                committed = [res for _eid, res in commit_members]
                eval_ids = [eid for eid, _res in commit_members]
                if self.commit_merged is not None:
                    index = self.commit_merged(committed, eval_ids, evals)
                elif self.commit is not None:
                    # merged callback not wired: stay correct with
                    # per-member commits (evals ride the first one)
                    index = 0
                    for i, (eid, res) in enumerate(commit_members):
                        index = self.commit(
                            res, eid, evals if i == 0 else []
                        )
                else:
                    index = self.store.latest_index + 1
                    self.store.upsert_merged_plan_results(index, committed)
                    if evals:
                        self.store.upsert_evals(
                            self.store.latest_index + 1, evals
                        )
                metrics.incr("nomad.plan.commits")
                metrics.incr(
                    "nomad.plan.committed_plans", len(commit_members)
                )
                metrics.incr("nomad.plan.merged_commits")
                metrics.incr(
                    "nomad.plan.merged_members", len(commit_members)
                )
                for _eid, res in commit_members:
                    res.alloc_index = index
                note_committed(fresh)
                if evals and self.on_evals_created is not None:
                    self.on_evals_created([
                        self.store.eval_by_id(e.id) or e for e in evals
                    ])
            commit_s = time.perf_counter() - t0
            for res in results:
                if res.rejected_nodes:
                    res.refresh_index = self.store.latest_index
            apply_s = time.perf_counter() - t_apply
            metrics.measure("nomad.plan.apply", apply_s)
            return results, {
                "apply_s": apply_s,
                "evaluate_s": evaluate_s,
                "commit_s": commit_s,
            }
