"""PlanQueue — priority-ordered pending plans with result futures.

Reference: nomad/plan_queue.go (:29-60) and the planApply loop
(nomad/plan_apply.go:71-178), which pipelines: while plan N's Raft commit
is in flight, plan N+1 is already being evaluated against the optimistic
post-N snapshot — worth keeping because evaluation (fit re-check) and
commit (log write) use different resources. Here the applier thread
evaluates the next plan while the store upsert of the previous one
completes asynchronously is a no-op (in-memory store), but the structure
is retained so a durable log can slot in.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from concurrent.futures import Future
from typing import Optional

from ..obs.trace import global_tracer as tracer
from ..structs import Plan, PlanResult
from ..utils.metrics import global_metrics as metrics
from .plan_apply import PlanApplier


class PendingPlan:
    __slots__ = ("plan", "future", "trace_ctx", "enqueued_at")

    def __init__(self, plan: Plan, trace_ctx=None):
        self.plan = plan
        self.future: Future[PlanResult] = Future()
        # the submitting worker's span context rides the queue so the
        # applier thread parents its spans into the right eval trace
        self.trace_ctx = trace_ctx
        self.enqueued_at = time.perf_counter()


class PlanQueue:
    def __init__(self):
        self._lock = threading.Condition()
        self._heap: list[tuple] = []
        self._c = itertools.count()
        self.enabled = False

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self.enabled = enabled
            if not enabled:
                for _, _, pending in self._heap:
                    pending.future.cancel()
                self._heap.clear()
            self._lock.notify_all()

    def enqueue(self, plan: Plan) -> Future:
        with self._lock:
            if not self.enabled:
                f: Future = Future()
                f.set_exception(RuntimeError("plan queue is disabled"))
                return f
            pending = PendingPlan(plan, trace_ctx=tracer.current_ctx())
            heapq.heappush(self._heap, (-plan.priority, next(self._c), pending))
            metrics.set_gauge("nomad.plan.queue_depth", len(self._heap))
            self._lock.notify_all()
            return pending.future

    def pop(self, timeout: float = 1.0) -> Optional[PendingPlan]:
        with self._lock:
            if not self._heap:
                self._lock.wait(timeout)
            if not self._heap:
                return None
            return heapq.heappop(self._heap)[2]

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)


class PlanApplyLoop:
    """The leader's serialized applier thread (plan_apply.go:71-178)."""

    def __init__(self, store, queue: PlanQueue, on_evals_created=None,
                 commit=None):
        self.applier = PlanApplier(
            store, on_evals_created=on_evals_created, commit=commit
        )
        self.queue = queue
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="plan-apply", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.is_set():
            pending = self.queue.pop(timeout=0.2)
            if pending is None:
                continue
            ctx = pending.trace_ctx
            if ctx is not None:
                tracer.add_span(
                    ctx.trace_id,
                    "plan_queue.wait",
                    time.perf_counter() - pending.enqueued_at,
                    parent=ctx,
                )
            try:
                # cross-thread adoption: plan_apply spans below parent
                # under the worker's submit_plan span
                with tracer.attach(ctx):
                    result = self.applier.apply(pending.plan)
                pending.future.set_result(result)
            except Exception as e:  # noqa: BLE001 — propagate to waiter
                pending.future.set_exception(e)
