"""PlanQueue — priority-ordered pending plans with result futures.

Reference: nomad/plan_queue.go (:29-60) and the planApply loop
(nomad/plan_apply.go:71-178), which pipelines: while plan N's Raft commit
is in flight, plan N+1 is already being evaluated against the optimistic
post-N snapshot — worth keeping because evaluation (fit re-check) and
commit (log write) use different resources. Here the applier thread
evaluates the next plan while the store upsert of the previous one
completes asynchronously is a no-op (in-memory store), but the structure
is retained so a durable log can slot in.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time
from concurrent.futures import Future
from typing import Optional

from ..chaos.plane import chaos_site
from ..obs.trace import global_tracer as tracer
from ..structs import MergedPlan, Plan, PlanResult
from ..utils.metrics import global_metrics as metrics
from .plan_apply import PlanApplier

log = logging.getLogger(__name__)


class PendingPlan:
    __slots__ = ("plan", "future", "trace_ctx", "enqueued_at")

    def __init__(self, plan: Plan, trace_ctx=None):
        self.plan = plan
        self.future: Future[PlanResult] = Future()
        # the submitting worker's span context rides the queue so the
        # applier thread parents its spans into the right eval trace
        self.trace_ctx = trace_ctx
        self.enqueued_at = time.perf_counter()

    def cancel(self) -> None:
        self.future.cancel()


class PendingMergedPlan:
    """One queue entry for a whole batched pass: B member plans, B result
    futures — the coalesced commit unit the merged-apply path consumes."""

    __slots__ = ("mplan", "futures", "trace_ctxs", "enqueued_at")

    def __init__(self, mplan: MergedPlan, trace_ctxs=None):
        self.mplan = mplan
        self.futures: list[Future] = [Future() for _ in mplan.plans]
        # one span context per member, so the applier thread records the
        # shared merged-apply phases into every member's trace
        self.trace_ctxs = list(trace_ctxs or [None] * len(mplan.plans))
        self.enqueued_at = time.perf_counter()

    def cancel(self) -> None:
        for f in self.futures:
            f.cancel()


class PlanQueue:
    def __init__(self):
        self._lock = threading.Condition()
        self._heap: list[tuple] = []
        self._c = itertools.count()
        self.enabled = False

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self.enabled = enabled
            if not enabled:
                for _, _, pending in self._heap:
                    pending.cancel()
                self._heap.clear()
            self._lock.notify_all()

    def enqueue(self, plan: Plan) -> Future:
        # raise faults here surface on the submitting worker, which
        # must nack the eval back to the broker for redelivery
        chaos_site("plan_queue.enqueue")
        with self._lock:
            if not self.enabled:
                f: Future = Future()
                f.set_exception(RuntimeError("plan queue is disabled"))
                return f
            pending = PendingPlan(plan, trace_ctx=tracer.current_ctx())
            heapq.heappush(self._heap, (-plan.priority, next(self._c), pending))
            metrics.set_gauge("nomad.plan.queue_depth", len(self._heap))
            self._lock.notify_all()
            return pending.future

    def enqueue_merged(
        self, mplan: MergedPlan, trace_ctxs=None
    ) -> list[Future]:
        """Submit a whole batched pass as ONE pending entry; returns one
        result future per member plan, resolved together when the merged
        apply lands."""
        # the caller is the worker's commit thread: a kill fault here is
        # the "crash mid merged-plan submit" scenario — nothing enqueued,
        # the batch's evals stay unacked, the deadline sweep redelivers
        chaos_site("plan_queue.enqueue_merged")
        with self._lock:
            if not self.enabled:
                futures: list[Future] = []
                for _ in mplan.plans:
                    f: Future = Future()
                    f.set_exception(RuntimeError("plan queue is disabled"))
                    futures.append(f)
                return futures
            pending = PendingMergedPlan(mplan, trace_ctxs=trace_ctxs)
            heapq.heappush(
                self._heap, (-mplan.priority, next(self._c), pending)
            )
            metrics.set_gauge("nomad.plan.queue_depth", len(self._heap))
            self._lock.notify_all()
            return pending.futures

    def pop(self, timeout: float = 1.0) -> Optional[PendingPlan]:
        with self._lock:
            if not self._heap:
                self._lock.wait(timeout)
            if not self._heap:
                return None
            return heapq.heappop(self._heap)[2]

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)


class PlanApplyLoop:
    """The leader's serialized applier thread (plan_apply.go:71-178)."""

    def __init__(self, store, queue: PlanQueue, on_evals_created=None,
                 commit=None, commit_merged=None, lanes=None,
                 token_check=None):
        self.applier = PlanApplier(
            store, on_evals_created=on_evals_created, commit=commit,
            commit_merged=commit_merged, lanes=lanes,
            token_check=token_check,
        )
        self.queue = queue
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="plan-apply", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.is_set():
            pending = self.queue.pop(timeout=0.2)
            if pending is None:
                continue
            if isinstance(pending, PendingMergedPlan):
                self._apply_merged(pending)
                continue
            ctx = pending.trace_ctx
            if ctx is not None:
                tracer.add_span(
                    ctx.trace_id,
                    "plan_queue.wait",
                    time.perf_counter() - pending.enqueued_at,
                    parent=ctx,
                )
            try:
                # cross-thread adoption: plan_apply spans below parent
                # under the worker's submit_plan span
                with tracer.attach(ctx):
                    result = self.applier.apply(pending.plan)
                pending.future.set_result(result)
            except Exception as e:  # noqa: BLE001 — propagate to waiter
                pending.future.set_exception(e)

    def _apply_merged(self, pending: PendingMergedPlan) -> None:
        """Apply one merged batch and resolve every member future; the
        shared queue-wait and apply phases are recorded into each
        member's trace (the batch-wide ``shared`` span convention)."""
        wait_s = time.perf_counter() - pending.enqueued_at
        mplan = pending.mplan
        try:
            results, timings = self.applier.apply_merged(mplan)
        except Exception as e:  # noqa: BLE001 — propagate to waiters
            log.exception("merged plan apply failed (%d members)",
                          len(mplan.plans))
            for f in pending.futures:
                if not f.done():
                    f.set_exception(e)
            return
        n = len(mplan.plans)
        for mp, res, fut, ctx in zip(
            mplan.plans, results, pending.futures, pending.trace_ctxs
        ):
            if ctx is not None:
                eid = mp.eval_id
                tracer.add_span(
                    eid, "plan_queue.wait", wait_s,
                    parent=ctx, tags={"shared": True},
                )
                sp = tracer.add_span(
                    eid, "plan_apply", timings["apply_s"], parent=ctx,
                    tags={
                        "shared": True,
                        "members": n,
                        "rejected_nodes": len(res.rejected_nodes),
                    },
                )
                if sp is not None:
                    tracer.add_span(
                        eid, "plan_apply.evaluate", timings["evaluate_s"],
                        parent=sp, tags={"shared": True},
                    )
                    tracer.add_span(
                        eid, "plan_apply.commit", timings["commit_s"],
                        parent=sp, tags={"shared": True},
                    )
            fut.set_result(res)
