"""EvalBroker — leader-side at-least-once priority queue of evaluations.

Reference: nomad/eval_broker.go (:47-105 EvalBroker, :182 Enqueue, blocking
Dequeue with per-scheduler-type ready queues, Ack/Nack with unack tracking,
nack redelivery with delay, DeliveryLimit → _failed queue, delayheap for
WaitUntil evals, per-job serialization: at most one eval per job in flight,
later ones deferred until the outstanding one is acked).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
import uuid
import zlib
from typing import Optional

from ..chaos.plane import chaos_site
from ..structs import Evaluation
from ..structs.evaluation import EVAL_DELIVERY_LIMIT

FAILED_QUEUE = "_failed"
DEFAULT_NACK_DELAY = 5.0
DEFAULT_INITIAL_NACK_DELAY = 1.0
# redelivery deadline for dequeued-but-unacked evals: a worker that dies
# mid-eval (crash, hung commit) would otherwise strand its evals — and,
# through per-job serialization, every later eval of the same jobs —
# forever. Sized well past the worker's longest internal wait (the 30 s
# plan future timeout) so slow-but-alive workers don't double-deliver.
DEFAULT_UNACK_TIMEOUT = 60.0


class _PQ:
    """Priority queue: higher eval priority first, FIFO within priority."""

    def __init__(self):
        self._h: list[tuple] = []
        self._c = itertools.count()

    def push(self, ev: Evaluation) -> None:
        heapq.heappush(self._h, (-ev.priority, next(self._c), ev))

    def pop(self) -> Optional[Evaluation]:
        if not self._h:
            return None
        return heapq.heappop(self._h)[2]

    def peek(self) -> Optional[Evaluation]:
        return self._h[0][2] if self._h else None

    def __len__(self):
        return len(self._h)


class EvalBroker:
    def __init__(
        self,
        nack_delay: float = DEFAULT_NACK_DELAY,
        initial_nack_delay: float = DEFAULT_INITIAL_NACK_DELAY,
        delivery_limit: int = EVAL_DELIVERY_LIMIT,
        n_partitions: int = 1,
        unack_timeout: Optional[float] = DEFAULT_UNACK_TIMEOUT,
        clock=None,
        admission=None,
    ):
        self._lock = threading.Condition()
        self.enabled = False
        # overload gate (server/admission.py AdmissionController, set by
        # the composition root): consulted on every enqueue with the
        # backlog depth the broker already holds, so over-watermark
        # external evals park on the delayed heap instead of piling
        # into ready. None (unit tests, standalone brokers) = no gate.
        self.admission = admission
        # injectable wall clock (the GenericScheduler clock= pattern,
        # NTA008): delay-heap firing times and unack redelivery
        # deadlines all read it, so chaos clock-skew faults reach the
        # broker's time-based behavior
        self._clock = clock if clock is not None else time.time
        self.nack_delay = nack_delay
        self.initial_nack_delay = initial_nack_delay
        self.delivery_limit = delivery_limit
        # None disables the redelivery deadline (tests that hold evals
        # outstanding across arbitrary debugger pauses)
        self.unack_timeout = unack_timeout
        # Eval-stream partitioning for CONCURRENT batching workers: each
        # eval's job hashes onto one of n_partitions sub-queues, and a
        # batching worker dequeues only its own partition — two batched
        # passes therefore never carry evals of the same job set, and
        # with per-worker lane striping (decorrelate_salt) they rarely
        # share hot nodes. n_partitions=1 keeps the original single
        # ready-queue-per-type behavior.
        self.n_partitions = max(1, n_partitions)
        # scheduler type (or "type#pN" when partitioned) → ready queue
        self._ready: dict[str, _PQ] = {}
        # eval id → (eval, token, redelivery deadline) while unacked
        self._unack: dict[str, tuple[Evaluation, str, float]] = {}
        # (ns, job id) → deferred evals waiting for the in-flight one
        self._pending_by_job: dict[tuple[str, str], _PQ] = {}
        self._in_flight_jobs: set[tuple[str, str]] = set()
        # delayed: (fire_time, seq, eval, type) heap for WaitUntil + nacks
        self._delayed: list[tuple] = []
        self._seq = itertools.count()
        self._delivery_count: dict[str, int] = {}
        # queue-wait attribution for the trace layer: eval id → wall clock
        # of first readiness, converted at dequeue into a wait the worker
        # collects via take_queue_wait() for the dequeue span's tags
        self._enqueued_at: dict[str, float] = {}
        self._queue_waits: dict[str, float] = {}
        self.stats = {
            "total_ready": 0,
            "total_unacked": 0,
            "total_blocked_on_job": 0,
            "total_waiting": 0,
            "total_failed": 0,
        }
        # at-least-once conservation ledger (chaos invariant: every
        # dequeue resolves as exactly one ack, nack, or unack timeout)
        self.counters = {
            "enqueues": 0,
            "dequeues": 0,
            "acks": 0,
            "nacks": 0,
            "unack_timeouts": 0,
            "admission_deferred": 0,
            "chaos_dup_enqueues": 0,
            "chaos_dropped_deliveries": 0,
        }

    # -- lifecycle ---------------------------------------------------------
    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self.enabled = enabled
            if not enabled:
                self._ready.clear()
                self._unack.clear()
                self._pending_by_job.clear()
                self._in_flight_jobs.clear()
                self._delayed.clear()
                self._delivery_count.clear()
                self._enqueued_at.clear()
                self._queue_waits.clear()
            self._lock.notify_all()

    # -- enqueue -----------------------------------------------------------
    def enqueue(self, ev: Evaluation) -> None:
        with self._lock:
            self._enqueue_locked(ev)
            self._lock.notify_all()

    def enqueue_all(self, evals: list[Evaluation]) -> None:
        with self._lock:
            for ev in evals:
                self._enqueue_locked(ev)
            self._lock.notify_all()

    def _enqueue_locked(self, ev: Evaluation, ignore_job_gate: bool = False) -> None:
        if not self.enabled:
            return
        self.counters["enqueues"] += 1
        now = self._clock()
        if ev.wait_until_unix and ev.wait_until_unix > now:
            heapq.heappush(
                self._delayed, (ev.wait_until_unix, next(self._seq), ev)
            )
            return
        # stamp first readiness (delayed evals stamp when they fire; the
        # job-gate defer still counts — that IS queue wait for the job)
        self._enqueued_at.setdefault(ev.id, now)
        # per-priority admission watermarks: past the brownout point,
        # externally-submitted evals whose tier watermark is below the
        # active backlog park on the delayed heap and re-decide when
        # they fire (each pass is one conservation-counted decision).
        # Liveness traffic is exempt inside the gate; a committed eval
        # is only ever DEFERRED here, never dropped (law 7).
        adm = self.admission
        if adm is not None:
            backlog = len(self._unack) + sum(
                len(q) for t, q in self._ready.items() if t != FAILED_QUEUE
            )
            delay = adm.gate_enqueue(ev, backlog)
            if delay is not None:
                self.counters["admission_deferred"] += 1
                heapq.heappush(self._delayed, (now + delay, next(self._seq), ev))
                return
        job_key = (ev.namespace, ev.job_id)
        if not ignore_job_gate and job_key in self._in_flight_jobs:
            self._pending_by_job.setdefault(job_key, _PQ()).push(ev)
            return
        self._ready.setdefault(self._queue_key(ev), _PQ()).push(ev)
        from ..utils.metrics import global_metrics

        global_metrics.set_gauge(
            "nomad.broker.total_ready",
            sum(len(q) for t, q in self._ready.items() if t != FAILED_QUEUE),
        )

    def _drain_delayed_locked(self) -> float:
        """Move due delayed evals to ready; return seconds to next firing."""
        now = self._clock()
        wait = 3600.0
        while self._delayed:
            fire, _, ev = self._delayed[0]
            if fire <= now:
                heapq.heappop(self._delayed)
                ev2 = ev
                ev2.wait_until_unix = 0.0
                self._enqueue_locked(ev2)
            else:
                wait = fire - now
                break
        # redelivery deadline sweep: evals whose dequeuing worker never
        # acked or nacked within unack_timeout go back through the normal
        # nack path (backoff redelivery, _failed past the delivery limit)
        if self.unack_timeout is not None:
            expired = [
                eid
                for eid, (_ev, _tok, deadline) in self._unack.items()
                if deadline <= now
            ]
            for eid in expired:
                ev, _tok, _deadline = self._unack.pop(eid)
                self._queue_waits.pop(eid, None)
                from ..utils.metrics import global_metrics

                global_metrics.incr("nomad.broker.unack_timeouts")
                self.counters["unack_timeouts"] += 1
                self._redeliver_locked(ev)
            for _ev, _tok, deadline in self._unack.values():
                wait = min(wait, max(deadline - now, 0.001))
        return wait

    # -- dequeue -----------------------------------------------------------
    def _queue_key(self, ev: Evaluation) -> str:
        if self.n_partitions == 1:
            return ev.type
        part = zlib.crc32(
            f"{ev.namespace}/{ev.job_id}".encode()
        ) % self.n_partitions
        return f"{ev.type}#p{part}"

    def _scan_keys(
        self, schedulers: list[str], partition
    ) -> list[str]:
        """``partition`` may be None (scan everything), a single int, or
        a tuple/list of ints — lane mode hands each batching worker its
        owned lane SET so dequeue is lane-affine by construction."""
        if self.n_partitions == 1:
            return list(schedulers)
        if isinstance(partition, int):
            partition = (partition,)
        keys = []
        for t in schedulers:
            if t == FAILED_QUEUE:
                keys.append(t)  # the failed queue is never partitioned
            elif partition is None:
                keys.extend(
                    f"{t}#p{p}" for p in range(self.n_partitions)
                )
            else:
                keys.extend(
                    f"{t}#p{p % self.n_partitions}" for p in partition
                )
        return keys

    def dequeue(
        self,
        schedulers: list[str],
        timeout: Optional[float] = None,
        partition: Optional[int | tuple[int, ...]] = None,
    ) -> tuple[Optional[Evaluation], str]:
        """Blocking dequeue for the given scheduler types. Returns
        (eval, token) or (None, "") on timeout/disable. ``timeout=None``
        blocks until an eval arrives (the reference's blocking
        Eval.Dequeue RPC, nomad/eval_broker.go); ``timeout=0`` is an
        explicit non-blocking poll. ``partition`` restricts the scan to
        one job-hash partition, or a lane set when given a tuple
        (deterministic lane ownership); None scans every partition."""
        deadline = None if timeout is None else self._clock() + timeout
        keys = self._scan_keys(schedulers, partition)
        with self._lock:
            while True:
                if not self.enabled:
                    return None, ""
                next_delay = self._drain_delayed_locked()
                best: Optional[_PQ] = None
                for t in keys:
                    q = self._ready.get(t)
                    if not q:
                        continue
                    # defer ready evals whose job already has one in flight
                    # (per-job serialization also applies to evals enqueued
                    # before the first one was dequeued)
                    while len(q):
                        cand = q.peek()
                        job_key = (cand.namespace, cand.job_id)
                        if job_key in self._in_flight_jobs:
                            q.pop()
                            self._pending_by_job.setdefault(job_key, _PQ()).push(
                                cand
                            )
                            continue
                        break
                    if len(q):
                        cand = q.peek()
                        if best is None or cand.priority > best.peek().priority:
                            best = q
                if best is not None:
                    ev = best.pop()
                    token = str(uuid.uuid4())
                    deadline = (
                        self._clock() + self.unack_timeout
                        if self.unack_timeout is not None
                        else float("inf")
                    )
                    self._unack[ev.id] = (ev, token, deadline)
                    self._in_flight_jobs.add((ev.namespace, ev.job_id))
                    self._delivery_count[ev.id] = (
                        self._delivery_count.get(ev.id, 0) + 1
                    )
                    self.counters["dequeues"] += 1
                    t_ready = self._enqueued_at.pop(ev.id, None)
                    if t_ready is not None:
                        self._queue_waits[ev.id] = self._clock() - t_ready
                    if chaos_site("broker.dequeue") == "drop":
                        # delivered-but-lost: the eval is charged as a
                        # dequeue and sits unacked, so the redelivery
                        # deadline sweep must hand it out exactly once
                        # more — the caller sees an empty poll
                        self.counters["chaos_dropped_deliveries"] += 1
                        self._queue_waits.pop(ev.id, None)
                        return None, ""
                    return ev, token
                if deadline is None:
                    self._lock.wait(min(next_delay, 1.0))
                    continue
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return None, ""
                self._lock.wait(min(remaining, next_delay, 1.0))

    def dequeue_many(
        self,
        schedulers: list[str],
        max_n: int,
        timeout: Optional[float] = None,
        partition: Optional[int | tuple[int, ...]] = None,
    ) -> list[tuple[Evaluation, str]]:
        """Dequeue up to ``max_n`` ready evals in one call — the intake of
        the batched multi-eval device pass (SURVEY.md §7 step 5). The
        first eval blocks up to ``timeout``; the rest are taken only if
        immediately ready. Per-job serialization holds: two evals of one
        job can never be in the same batch (or in flight at all)."""
        first = self.dequeue(schedulers, timeout=timeout, partition=partition)
        if first[0] is None:
            return []
        out = [first]
        while len(out) < max_n:
            nxt = self.dequeue(schedulers, timeout=0.0, partition=partition)
            if nxt[0] is None:
                break
            out.append(nxt)
        return out

    # -- ack / nack --------------------------------------------------------
    def _validate(self, eval_id: str, token: str) -> Evaluation:
        entry = self._unack.get(eval_id)
        if entry is None:
            raise ValueError(f"eval {eval_id} not outstanding")
        ev, tok, _deadline = entry
        if tok != token:
            raise ValueError("token mismatch")
        return ev

    def _promote_pending_locked(self, job_key: tuple[str, str]) -> None:
        """Release the next deferred eval for a job whose gate opened."""
        pq = self._pending_by_job.get(job_key)
        if pq is not None and len(pq):
            nxt = pq.pop()
            if not len(pq):
                del self._pending_by_job[job_key]
            self._enqueue_locked(nxt)

    def take_queue_wait(self, eval_id: str) -> float:
        """Pop the ready→dequeue wait recorded for an eval (seconds);
        0.0 when unknown. The dequeuing worker calls this exactly once to
        tag the trace's dequeue span, so the table never accumulates."""
        with self._lock:
            return self._queue_waits.pop(eval_id, 0.0)

    def ack(self, eval_id: str, token: str) -> None:
        # consulted outside the lock: a "delay" here models a *late*
        # ack, which may lose the race against the unack-deadline sweep
        # (the worker then sees ValueError, a swallow site it accounts)
        action = chaos_site("broker.ack")
        if action == "drop":
            # lost ack: the eval stays unacked and the deadline sweep
            # redelivers it — reprocessing must converge to a no-op
            return
        with self._lock:
            ev = self._validate(eval_id, token)
            del self._unack[eval_id]
            self.counters["acks"] += 1
            self._delivery_count.pop(eval_id, None)
            self._queue_waits.pop(eval_id, None)
            job_key = (ev.namespace, ev.job_id)
            self._in_flight_jobs.discard(job_key)
            self._promote_pending_locked(job_key)
            if action == "duplicate":
                # at-least-once duplicate delivery: the acked eval is
                # re-enqueued once (behind the job gate, like any real
                # duplicate) and must reprocess to a no-op
                self.counters["chaos_dup_enqueues"] += 1
                self._enqueue_locked(ev)
            self._lock.notify_all()

    def nack(self, eval_id: str, token: str) -> None:
        """Failed processing: redeliver after a backoff, unless the
        delivery limit is reached — then route to the _failed queue."""
        with self._lock:
            ev = self._validate(eval_id, token)
            del self._unack[eval_id]
            self.counters["nacks"] += 1
            self._queue_waits.pop(eval_id, None)
            self._redeliver_locked(ev)
            self._lock.notify_all()

    def _redeliver_locked(self, ev: Evaluation) -> None:
        """Shared tail of an explicit nack and an unack-deadline expiry:
        release the job gate, then backoff-redeliver or fail out."""
        job_key = (ev.namespace, ev.job_id)
        self._in_flight_jobs.discard(job_key)
        count = self._delivery_count.get(ev.id, 0)
        if count >= self.delivery_limit:
            self._ready.setdefault(FAILED_QUEUE, _PQ()).push(ev)
            # the job's gate is permanently released for this eval —
            # deferred evals must not be stranded behind it
            self._promote_pending_locked(job_key)
        else:
            # attempt-indexed escalation: first redelivery waits
            # initial_nack_delay, each further one doubles, capped at
            # nack_delay — a hot-looping eval (processing-deadline
            # expiry, flapping device) cannot spin dequeue/nack at full
            # broker speed (eval_broker.go computes the same
            # per-attempt wait before re-enqueueing)
            delay = min(
                self.nack_delay,
                self.initial_nack_delay * (2.0 ** max(0, count - 1)),
            )
            from ..utils.metrics import global_metrics

            global_metrics.incr("nomad.broker.nack_redelivery_delayed")
            heapq.heappush(
                self._delayed,
                (self._clock() + delay, next(self._seq), ev),
            )

    # -- introspection -----------------------------------------------------
    def outstanding(self, eval_id: str) -> bool:
        with self._lock:
            return eval_id in self._unack

    def outstanding_token(self, eval_id: str) -> str:
        with self._lock:
            entry = self._unack.get(eval_id)
            return entry[1] if entry else ""

    def ready_count(self) -> int:
        with self._lock:
            return sum(len(q) for t, q in self._ready.items() if t != FAILED_QUEUE)

    def failed_count(self) -> int:
        with self._lock:
            q = self._ready.get(FAILED_QUEUE)
            return len(q) if q else 0

    def failed_eval_ids(self) -> list[str]:
        """Evals parked past the delivery limit (chaos accounting: a
        failed eval explains a job stuck short of its desired count)."""
        with self._lock:
            q = self._ready.get(FAILED_QUEUE)
            return [entry[2].id for entry in q._h] if q else []

    def tracked_eval_ids(self) -> set[str]:
        """Every eval id the broker still holds anywhere — ready
        queues, unacked, delayed heap, or deferred behind a job gate.
        The chaos invariant checker uses this to prove no non-terminal
        eval in the store has been stranded."""
        with self._lock:
            ids: set[str] = set()
            for q in self._ready.values():
                ids.update(entry[2].id for entry in q._h)
            ids.update(self._unack.keys())
            ids.update(entry[2].id for entry in self._delayed)
            for q in self._pending_by_job.values():
                ids.update(entry[2].id for entry in q._h)
            return ids

    def queue_depths(self) -> dict[str, int]:
        """One consistent snapshot of every queue depth (the chaos
        runner's quiesce predicate: all zeros except _failed)."""
        with self._lock:
            return {
                "ready": sum(
                    len(q) for t, q in self._ready.items() if t != FAILED_QUEUE
                ),
                "unacked": len(self._unack),
                "delayed": len(self._delayed),
                "deferred": sum(len(q) for q in self._pending_by_job.values()),
                "failed": len(self._ready.get(FAILED_QUEUE, ())),
            }
