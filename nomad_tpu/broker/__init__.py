"""Leader-side queues and the serialized plan applier."""

from .plan_apply import PlanApplier, evaluate_node_plan, evaluate_plan

__all__ = ["PlanApplier", "evaluate_plan", "evaluate_node_plan"]
