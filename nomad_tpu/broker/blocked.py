"""BlockedEvals — evals that failed placement wait here for capacity.

Reference: nomad/blocked_evals.go (:33-96). One blocked eval per job; a
capacity change (node registered/updated, alloc stopped) unblocks the
evals whose class eligibility doesn't rule the change out, re-enqueuing
them into the EvalBroker. Evals that escaped computed-class filtering
unblock on any change.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..structs import Evaluation
from ..structs.evaluation import EVAL_STATUS_PENDING, TRIGGER_QUEUED_ALLOCS


class BlockedEvals:
    def __init__(self, broker=None):
        self._lock = threading.Lock()
        self.broker = broker
        self.enabled = False
        # job key → blocked eval (one per job, blocked_evals.go:33)
        self._captured: dict[tuple[str, str], Evaluation] = {}
        # eval id → job key
        self._by_id: dict[str, tuple[str, str]] = {}
        # state index of the last capacity change — an eval whose snapshot
        # predates it missed an unblock and is released immediately
        # (blocked_evals.go missedUnblock / unblockIndexes)
        self._last_unblock_index = 0
        self.stats = {"total_blocked": 0, "total_escaped": 0, "total_unblocked": 0}

    def captured(self) -> list:
        """Snapshot of currently-parked blocked evals (bench/ops
        accounting: every unplaced alloc must be attributable —
        VERDICT r3 weak #4)."""
        with self._lock:
            return list(self._captured.values())

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self.enabled = enabled
            if not enabled:
                self._captured.clear()
                self._by_id.clear()

    def block(self, ev: Evaluation) -> None:
        with self._lock:
            if not self.enabled:
                return
            if ev.snapshot_index and ev.snapshot_index < self._last_unblock_index:
                # capacity changed after the scheduler's snapshot: the
                # unblock already happened, re-run immediately
                self.stats["total_unblocked"] += 1
                if self.broker is not None:
                    ev.status = EVAL_STATUS_PENDING
                    self.broker.enqueue(ev)
                return
            key = (ev.namespace, ev.job_id)
            old = self._captured.get(key)
            if old is not None and old.modify_index > ev.modify_index:
                return  # keep the newer one
            if old is not None:
                self._by_id.pop(old.id, None)
            self._captured[key] = ev
            self._by_id[ev.id] = key
            self.stats["total_blocked"] += 1
            if ev.escaped_computed_class:
                self.stats["total_escaped"] += 1

    def untrack(self, namespace: str, job_id: str) -> None:
        """Job deregistered/updated — its blocked eval is stale."""
        with self._lock:
            ev = self._captured.pop((namespace, job_id), None)
            if ev is not None:
                self._by_id.pop(ev.id, None)

    def unblock(
        self, computed_class: str = "", quota: str = "", index: int = 0
    ) -> list[Evaluation]:
        """Capacity changed (for nodes of ``computed_class``, or any when
        empty): release matching evals back to the broker. ``index`` is the
        state index of the change, recorded so in-flight evals that block
        afterwards know they missed it."""
        with self._lock:
            if not self.enabled:
                return []
            self._last_unblock_index = max(self._last_unblock_index, index)
            release: list[Evaluation] = []
            keep: dict[tuple[str, str], Evaluation] = {}
            for key, ev in self._captured.items():
                eligible = (
                    not computed_class
                    or ev.escaped_computed_class
                    or ev.class_eligibility.get(computed_class, True)
                )
                if eligible:
                    release.append(ev)
                    self._by_id.pop(ev.id, None)
                else:
                    keep[key] = ev
            self._captured = keep
            self.stats["total_unblocked"] += len(release)
        for ev in release:
            ev.status = EVAL_STATUS_PENDING
            ev.triggered_by = TRIGGER_QUEUED_ALLOCS
        if self.broker is not None and release:
            self.broker.enqueue_all(release)
        return release

    def blocked_count(self) -> int:
        with self._lock:
            return len(self._captured)

    def get_blocked(self, namespace: str, job_id: str) -> Optional[Evaluation]:
        with self._lock:
            return self._captured.get((namespace, job_id))
