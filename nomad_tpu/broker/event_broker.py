"""Event broker — in-memory pub/sub of state-change events.

Reference: nomad/stream/event_broker.go (:30-48) with its ring-buffer
eventBuffer and per-subscriber subscriptions feeding ``/v1/event/stream``
NDJSON (nomad/stream/ndjson.go). Publishers are the server's apply paths
(the reference publishes from state-store txn hooks, state/events.go).
"""

from __future__ import annotations

import itertools
import json
import threading
from dataclasses import dataclass, field
from typing import Iterator, Optional

DEFAULT_BUFFER_SIZE = 4096

TOPIC_JOB = "Job"
TOPIC_EVAL = "Evaluation"
TOPIC_ALLOC = "Allocation"
TOPIC_NODE = "Node"
TOPIC_DEPLOYMENT = "Deployment"


@dataclass(slots=True)
class Event:
    topic: str
    type: str
    key: str  # job id / node id / alloc id ...
    namespace: str = "default"
    index: int = 0
    payload: dict = field(default_factory=dict)
    # broker-assigned monotonic sequence — several events can share one
    # state index (e.g. a batched client sync), so subscribers track seq,
    # never index, to avoid missing same-index events published later
    seq: int = 0

    def to_json(self) -> str:
        return json.dumps(
            {
                "Topic": self.topic,
                "Type": self.type,
                "Key": self.key,
                "Namespace": self.namespace,
                "Index": self.index,
                "Payload": self.payload,
            }
        )


class EventBroker:
    def __init__(self, size: int = DEFAULT_BUFFER_SIZE):
        self._lock = threading.Condition()
        self.size = size
        self._buf: list[Event] = []
        self._seq = itertools.count(1)

    def publish(self, events: list[Event], index: int) -> None:
        with self._lock:
            for ev in events:
                ev.index = index
                ev.seq = next(self._seq)
                self._buf.append(ev)
            if len(self._buf) > self.size:
                del self._buf[: len(self._buf) - self.size]
            self._lock.notify_all()

    def subscribe(
        self,
        topics: Optional[dict[str, list[str]]] = None,
        from_index: int = 0,
    ) -> "Subscription":
        """``topics`` maps topic → keys ("*" for all), as in the reference's
        SubscribeRequest; None subscribes to everything."""
        return Subscription(self, topics, from_index)

    def _collect(self, topics, after_seq: int) -> list[Event]:
        out = []
        for ev in self._buf:
            if ev.seq <= after_seq:
                continue
            if topics:
                keys = topics.get(ev.topic) or topics.get("*")
                if keys is None:
                    continue
                if "*" not in keys and ev.key not in keys:
                    continue
            out.append(ev)
        return out


class Subscription:
    def __init__(self, broker: EventBroker, topics, from_index: int):
        self.broker = broker
        self.topics = topics
        self.closed = False
        # map the caller's index cursor to an internal seq cursor
        with broker._lock:
            self.last_seq = max(
                (ev.seq for ev in broker._buf if ev.index <= from_index),
                default=0,
            )

    def next_events(self, timeout: float = 1.0) -> list[Event]:
        """Block until events newer than the cursor arrive (or timeout)."""
        with self.broker._lock:
            events = self.broker._collect(self.topics, self.last_seq)
            if not events:
                self.broker._lock.wait(timeout)
                events = self.broker._collect(self.topics, self.last_seq)
            if events:
                self.last_seq = max(ev.seq for ev in events)
            return events

    def stream(self, poll_timeout: float = 1.0) -> Iterator[Event]:
        while not self.closed:
            for ev in self.next_events(timeout=poll_timeout):
                yield ev

    def close(self) -> None:
        self.closed = True
