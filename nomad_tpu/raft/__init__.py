"""Raft consensus — the replicated-log backbone of the control plane.

Reference: hashicorp/raft wired in nomad/server.go:105-109 with the
raft-boltdb log store and the FSM in nomad/fsm.go. Here the log rides the
native C++ WAL (nomad_tpu.native), RPCs ride nomad_tpu.rpc, and the FSM is
nomad_tpu.server.fsm.

Two implementations share the contract:
- ``InlineRaft`` — the single-server fast path (dev agent): serialized
  append→apply with optional WAL durability and replay-on-boot.
- ``RaftNode`` — full consensus: leader election, log replication,
  commitment, snapshot install, membership-static peer set.
"""

from .inline import InlineRaft
from .node import NotLeaderError, RaftNode

__all__ = ["InlineRaft", "RaftNode", "NotLeaderError"]
