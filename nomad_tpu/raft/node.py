"""RaftNode — leader election, log replication, commitment, snapshots.

Reference: hashicorp/raft as wired by nomad/server.go:105-109 (transport:
nomad/raft_rpc.go RaftLayer; log store: raft-boltdb). The protocol here is
standard Raft (elections with randomized timeouts, log-matching append
entries, majority commitment, snapshot install for lagging followers),
persisted in the native C++ WAL (term/vote in its KV, entries in the
segmented log) and transported over nomad_tpu.rpc.

Scope notes vs hashicorp/raft: peer ADDITION is static per process
lifetime (join = restart with new config); peer REMOVAL is dynamic — a
RAFT_REMOVE_PEER entry committed through the log (the single-server
membership-change special case; hashicorp/raft RemoveServer), consumed by
autopilot dead-server cleanup (nomad/autopilot.go) and `operator raft
remove-peer` (command/operator_raft_remove.go). Pre-vote and leadership
transfer are not implemented.
"""

from __future__ import annotations

import logging
import os
import pickle
import random
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from ..rpc import RPCClient

log = logging.getLogger(__name__)

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

MAX_BATCH_ENTRIES = 512
SNAP_THRESHOLD_ENTRIES = 8192


class NotLeaderError(Exception):
    def __init__(self, leader_id: Optional[str], leader_addr: Optional[str]):
        super().__init__(f"not the leader (leader={leader_id})")
        self.leader_id = leader_id
        self.leader_addr = leader_addr


@dataclass
class RaftConfig:
    node_id: str
    peers: Dict[str, str]  # node_id -> rpc address (includes self)
    data_dir: Optional[str] = None
    election_timeout_min: float = 0.15
    election_timeout_max: float = 0.30
    heartbeat_interval: float = 0.05
    snapshot_threshold: int = SNAP_THRESHOLD_ENTRIES
    rpc_timeout: float = 2.0


class _MemLog:
    """In-memory log (tests / diskless mode); mirrors the WAL interface."""

    def __init__(self):
        self._e: dict[int, tuple[int, int, bytes]] = {}
        self._first = 0
        self._last = 0
        self._kv: dict[str, bytes] = {}

    def first_index(self):
        return self._first

    def last_index(self):
        return self._last

    def append(self, index, term, type_, data):
        self._e[index] = (term, type_, data)
        if self._first == 0:
            self._first = index
        self._last = index

    def get(self, index):
        if index not in self._e:
            raise KeyError(index)
        return self._e[index]

    def truncate_suffix(self, from_index):
        for i in range(from_index, self._last + 1):
            self._e.pop(i, None)
        if from_index <= self._first:
            self._first = self._last = 0
        else:
            self._last = from_index - 1

    def compact_prefix(self, to_index):
        for i in range(self._first, min(to_index, self._last) + 1):
            self._e.pop(i, None)
        if self._e:
            self._first = min(self._e)
        else:
            self._first = self._last = 0

    def sync(self):
        pass

    def close(self):
        pass

    def kv_set(self, k, v):
        self._kv[k] = v

    def kv_get(self, k):
        return self._kv.get(k)


class RaftNode:
    def __init__(self, config: RaftConfig, fsm,
                 snapshot_fn=None, restore_fn=None,
                 on_leader=None, on_follower=None):
        self.config = config
        self.fsm = fsm
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.on_leader = on_leader      # establishLeadership hook
        self.on_follower = on_follower  # revokeLeadership hook

        if config.data_dir:
            from ..native import WalStore

            os.makedirs(config.data_dir, exist_ok=True)
            self.log = WalStore(os.path.join(config.data_dir, "raft"))
        else:
            self.log = _MemLog()

        self._mu = threading.RLock()
        self.state = FOLLOWER
        self.term = self._load_u64("term")
        self.voted_for = self._load_str("voted_for")
        # Membership survives restart/compaction: a committed
        # RAFT_REMOVE_PEER persists the REMOVED-PEER SET (and our own
        # removed flag) in the node's durable KV. Without this, a restart
        # would revert to the full peer set and a restarted removed
        # server could campaign again — with enough reverted servers, two
        # disjoint quorums (split brain). Persisting the removed SET (not
        # the whole peer map) keeps the documented join-by-restart path
        # working: new peers and address changes still flow from the
        # static startup config; only removals are subtracted. Re-adding
        # a removed server requires wiping its entry (fresh data-dir /
        # operator action), the same contract as hashicorp/raft.
        removed_blob = self.log.kv_get("removed_peers")
        self._removed_peers: set = (
            pickle.loads(removed_blob) if removed_blob else set()
        )
        for rid in self._removed_peers:
            if rid != config.node_id:
                self.config.peers.pop(rid, None)
        self._removed_persisted = self.log.kv_get("removed") == b"1"
        self.leader: Optional[str] = None
        self.commit_index = 0
        self.last_applied = 0
        # snapshot bookkeeping: term of the entry the snapshot subsumes
        self.snap_index = self._load_u64("snap_index")
        self.snap_term = self._load_u64("snap_term")

        self._last_contact = time.monotonic()
        self._timeout = self._rand_timeout()
        self._closed = False
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._futures: dict[int, Future] = {}
        self._apply_cv = threading.Condition(self._mu)
        # serializes FSM mutation between the applier loop and
        # InstallSnapshot restore; always acquired BEFORE _mu
        self._apply_serial = threading.Lock()
        self._repl_events: dict[str, threading.Event] = {}
        self._clients: dict[str, RPCClient] = {}
        self._match_index: dict[str, int] = {}
        self._next_index: dict[str, int] = {}
        self._entries_since_snap = 0
        # set when a committed membership change removed THIS server from
        # the voting set: it stops starting elections (a removed server
        # kicking off term churn is the classic disruption autopilot's
        # dead-server cleanup exists to avoid)
        self._removed = self._removed_persisted
        # lame-duck replication: a peer removed from the config keeps
        # receiving append_entries until it ACKS the removal entry (so a
        # LIVE removed server learns it was removed instead of election-
        # timing-out and disrupting the survivors) or the grace expires
        # (a DEAD one can't ack). peer_id -> (removal_index, deadline).
        self._lame_ducks: dict[str, tuple[int, float]] = {}

    # -- persistence helpers ----------------------------------------------
    def _load_u64(self, key: str) -> int:
        v = self.log.kv_get(key)
        return int.from_bytes(v, "little") if v else 0

    def _load_str(self, key: str) -> Optional[str]:
        v = self.log.kv_get(key)
        return v.decode() if v else None

    def _persist_term_vote(self) -> None:
        self.log.kv_set("term", self.term.to_bytes(8, "little"))
        self.log.kv_set("voted_for", (self.voted_for or "").encode())

    def _persist_snap_meta(self) -> None:
        self.log.kv_set("snap_index", self.snap_index.to_bytes(8, "little"))
        self.log.kv_set("snap_term", self.snap_term.to_bytes(8, "little"))

    def _persist_membership_locked(self) -> None:
        """Durable membership: the config change must survive restart and
        log compaction (the removal entry itself can be compacted away).
        Only the removed SET is persisted — additions and address changes
        keep flowing from the static startup config."""
        self.log.kv_set(
            "removed_peers",
            pickle.dumps(self._removed_peers, pickle.HIGHEST_PROTOCOL),
        )
        self.log.kv_set("removed", b"1" if self._removed else b"0")
        self.log.sync()

    def _snap_path(self) -> str:
        return os.path.join(self.config.data_dir or "", "state.snap")

    # -- lifecycle ---------------------------------------------------------
    def start(self, rpc_server) -> None:
        """Register RPC handlers and start the election ticker. Boot-time
        recovery: restore newest snapshot, then trust the log (entries
        re-commit via normal protocol)."""
        if self.config.data_dir and self.restore_fn is not None and (
            os.path.exists(self._snap_path())
        ):
            self.restore_fn(self._snap_path())
        with self._mu:
            self.last_applied = self.fsm.store.latest_index
            self.commit_index = self.last_applied
        rpc_server.register("Raft.request_vote", self._handle_request_vote)
        rpc_server.register("Raft.append_entries", self._handle_append_entries)
        rpc_server.register("Raft.install_snapshot", self._handle_install_snapshot)
        t = threading.Thread(target=self._ticker, name="raft-ticker", daemon=True)
        t.start()
        self._threads.append(t)
        t2 = threading.Thread(target=self._applier, name="raft-apply", daemon=True)
        t2.start()
        self._threads.append(t2)

    def shutdown(self) -> None:
        self._stop.set()
        for ev in self._repl_events.values():
            ev.set()
        for c in self._clients.values():
            c.close()
        # close under _mu: every log access holds _mu, so this cannot race
        # an in-flight RPC handler into a use-after-free of the native WAL
        with self._mu:
            self._apply_cv.notify_all()
            if not self._closed:  # shutdown is idempotent
                self._closed = True
                self.log.sync()
                self.log.close()

    # -- helpers -----------------------------------------------------------
    def _rand_timeout(self) -> float:
        return random.uniform(
            self.config.election_timeout_min, self.config.election_timeout_max
        )

    def _client(self, peer_id: str) -> RPCClient:
        c = self._clients.get(peer_id)
        if c is None:
            # raft supplies its own retry cadence (heartbeat interval,
            # election timer); transport-level dial retries would stall
            # the timing the protocol depends on
            c = RPCClient(
                self.config.peers[peer_id], timeout=self.config.rpc_timeout,
                max_attempts=1,
            )
            self._clients[peer_id] = c
        return c

    def _last_log(self) -> Tuple[int, int]:
        """(last_index, last_term) including snapshot tail."""
        li = self.log.last_index()
        if li == 0:
            return self.snap_index, self.snap_term
        term, _t, _d = self.log.get(li)
        return li, term

    def _term_at(self, index: int) -> Optional[int]:
        if index == 0:
            return 0
        if index == self.snap_index:
            return self.snap_term
        try:
            term, _t, _d = self.log.get(index)
            return term
        except KeyError:
            return None

    def is_leader(self) -> bool:
        return self.state == LEADER

    def peers(self) -> Dict[str, str]:
        """Current voting configuration (node_id -> addr), self included."""
        with self._mu:
            return dict(self.config.peers)

    def remove_peer(self, node_id: str, timeout: float = 10.0) -> None:
        """Leader-only: commit a membership change removing ``node_id``
        from the voting set (autopilot / operator raft remove-peer). The
        change takes effect on each server as the entry applies; the
        removed server stops participating (no elections, no votes)."""
        from ..server.fsm import MsgType

        with self._mu:
            if node_id not in self.config.peers:
                raise ValueError(f"unknown raft peer {node_id!r}")
            if node_id == self.config.node_id:
                raise ValueError("cannot remove the current leader; "
                                 "transfer leadership first")
        self.apply(MsgType.RAFT_REMOVE_PEER, {"node_id": node_id},
                   timeout=timeout)

    def leader_id(self) -> Optional[str]:
        return self.leader

    def leader_addr(self) -> Optional[str]:
        return self.config.peers.get(self.leader) if self.leader else None

    def stats(self) -> dict:
        with self._mu:
            return {
                "state": self.state.capitalize(),
                "term": self.term,
                "leader": self.leader,
                "last_log_index": self._last_log()[0],
                "commit_index": self.commit_index,
                "applied_index": self.last_applied,
                "num_peers": len(self.config.peers) - 1,
                "snapshot_index": self.snap_index,
            }

    # -- public write path -------------------------------------------------
    def apply(self, mtype: int, payload: Optional[dict] = None,
              timeout: float = 10.0) -> Tuple[int, Any]:
        """Leader-only: append, replicate, wait for commit+apply, return
        (index, fsm_result). Raises NotLeaderError for forwarding."""
        with self._mu:
            if self._stop.is_set():
                raise NotLeaderError(None, None)
            if self.state != LEADER:
                raise NotLeaderError(self.leader, self.leader_addr())
            index = self._last_log()[0] + 1
            data = pickle.dumps(payload, pickle.HIGHEST_PROTOCOL)
            self.log.append(index, self.term, int(mtype), data)
            # Raft stable-storage rule: the leader's own vote toward the
            # commit majority only counts once the entry is durable — an
            # acked commit must survive leader power loss (followers fsync
            # in _handle_append_entries; the leader must too).
            self.log.sync()
            fut: Future = Future()
            self._futures[index] = fut
            self._maybe_advance_commit_locked()
        for ev in self._repl_events.values():
            ev.set()
        try:
            return index, fut.result(timeout=timeout)
        except TimeoutError:
            self._futures.pop(index, None)
            raise TimeoutError(
                f"raft apply at index {index} not committed within {timeout}s"
            ) from None

    def barrier(self, timeout: float = 10.0) -> int:
        from ..server.fsm import MsgType

        index, _ = self.apply(MsgType.NOOP, None, timeout=timeout)
        return index

    # -- ticker / elections ------------------------------------------------
    def _ticker(self) -> None:
        while not self._stop.wait(0.01):
            with self._mu:
                if self._stop.is_set():  # shutdown raced our wait: the log
                    return                # may already be closed
                if self.state == LEADER or self._removed:
                    continue
                if time.monotonic() - self._last_contact < self._timeout:
                    continue
                # election time
                self.state = CANDIDATE
                self.term += 1
                self.voted_for = self.config.node_id
                self._persist_term_vote()
                term = self.term
                self._last_contact = time.monotonic()
                self._timeout = self._rand_timeout()
                window = self._timeout
                last_index, last_term = self._last_log()
            self._run_election(term, last_index, last_term, window)

    def _run_election(self, term: int, last_index: int, last_term: int,
                      window: float) -> None:
        votes = [self.config.node_id]  # self-vote
        vote_mu = threading.Lock()
        done = threading.Event()
        majority = len(self.config.peers) // 2 + 1

        def ask(peer_id: str) -> None:
            try:
                resp = self._client(peer_id).call(
                    "Raft.request_vote",
                    {
                        "term": term,
                        "candidate_id": self.config.node_id,
                        "last_log_index": last_index,
                        "last_log_term": last_term,
                    },
                    timeout=self.config.rpc_timeout,
                )
            except Exception:
                return
            with self._mu:
                if resp["term"] > self.term:
                    self._step_down_locked(resp["term"])
                    done.set()
                    return
            if resp.get("granted"):
                with vote_mu:
                    votes.append(peer_id)
                    if len(votes) >= majority:
                        done.set()

        others = [p for p in self.config.peers if p != self.config.node_id]
        for p in others:
            threading.Thread(target=ask, args=(p,), daemon=True).start()
        if not others:
            done.set()
        # hold the candidacy open for the full randomized election window
        # (Raft §5.2): under load, grants can arrive later than a fixed
        # short wait, and discarding them forces needless re-elections
        deadline = time.monotonic() + window
        while not done.wait(timeout=0.02):
            with self._mu:
                if self.state != CANDIDATE or self.term != term:
                    return
            with vote_mu:
                if len(votes) >= majority:
                    break
            if time.monotonic() > deadline:
                break
        with self._mu:
            if self.state != CANDIDATE or self.term != term:
                return
            if len(votes) >= majority:
                self._become_leader_locked()

    def _become_leader_locked(self) -> None:
        log.info(
            "raft: %s won election for term %d", self.config.node_id, self.term
        )
        self.state = LEADER
        self.leader = self.config.node_id
        last, _ = self._last_log()
        self._next_index = {
            p: last + 1 for p in self.config.peers if p != self.config.node_id
        }
        self._match_index = {
            p: 0 for p in self.config.peers if p != self.config.node_id
        }
        # barrier entry: commits everything from prior terms (Raft §5.4.2 —
        # a leader may only count replicas for entries of its own term)
        from ..server.fsm import MsgType

        index = last + 1
        self.log.append(index, self.term, int(MsgType.NOOP), pickle.dumps(None))
        self.log.sync()  # durable before counting toward the majority
        self._maybe_advance_commit_locked()
        for p in self._next_index:
            ev = threading.Event()
            ev.set()
            self._repl_events[p] = ev
            t = threading.Thread(
                target=self._replicate_loop, args=(p, self.term),
                name=f"raft-repl-{p}", daemon=True,
            )
            t.start()
        if self.on_leader is not None:
            threading.Thread(target=self.on_leader, daemon=True).start()

    def _step_down_locked(self, new_term: int) -> None:
        was_leader = self.state == LEADER
        if new_term > self.term:
            self.term = new_term
            self.voted_for = None
            self._persist_term_vote()
        self.state = FOLLOWER
        self._last_contact = time.monotonic()
        self._timeout = self._rand_timeout()
        if was_leader:
            # fail in-flight futures: commitment now unknown
            for fut in self._futures.values():
                if not fut.done():
                    fut.set_exception(NotLeaderError(self.leader, None))
            self._futures.clear()
            if self.on_follower is not None:
                threading.Thread(target=self.on_follower, daemon=True).start()

    # -- replication (leader) ----------------------------------------------
    def _replicate_loop(self, peer_id: str, term: int) -> None:
        ev = self._repl_events[peer_id]
        while not self._stop.is_set():
            ev.wait(timeout=self.config.heartbeat_interval)
            ev.clear()
            with self._mu:
                if self._stop.is_set() or self.state != LEADER or (
                    self.term != term
                ):
                    return
                if peer_id not in self.config.peers:
                    duck = self._lame_ducks.get(peer_id)
                    if duck is None or time.monotonic() > duck[1]:
                        self._finalize_removed_peer_locked(peer_id)
                        return
                    # lame duck: keep feeding it the removal entry (and
                    # the commit index covering it — it ACKS the entry
                    # before it learns the commit, so finalizing on match
                    # alone would strand it unaware, election-timing-out)
                if peer_id not in self._next_index:
                    return
                next_idx = self._next_index[peer_id]
                first = self.log.first_index()
                need_snapshot = (
                    self.snap_index > 0 and next_idx <= self.snap_index and (
                        first == 0 or next_idx < first
                    )
                )
                if not need_snapshot:
                    batch, prev_index, prev_term, ok = (
                        self._build_batch_locked(next_idx)
                    )
                    if not ok:
                        need_snapshot = self.snap_index > 0
                commit = self.commit_index
            if need_snapshot:
                self._send_snapshot(peer_id, term)
                continue
            try:
                resp = self._client(peer_id).call(
                    "Raft.append_entries",
                    {
                        "term": term,
                        "leader_id": self.config.node_id,
                        "prev_log_index": prev_index,
                        "prev_log_term": prev_term,
                        "entries": batch,
                        "leader_commit": commit,
                    },
                    timeout=self.config.rpc_timeout,
                )
            except Exception:
                continue  # retry next tick
            with self._mu:
                if self.state != LEADER or self.term != term:
                    return
                is_duck = peer_id in self._lame_ducks
                if peer_id not in self.config.peers and not is_duck:
                    return
                if resp["term"] > self.term:
                    if is_duck:
                        # a removed-but-unaware server camps on inflated
                        # terms from its futile elections; its responses
                        # must not dethrone the surviving leader
                        continue
                    self._step_down_locked(resp["term"])
                    return
                if resp.get("success"):
                    if batch:
                        self._match_index[peer_id] = batch[-1][0]
                        self._next_index[peer_id] = batch[-1][0] + 1
                        self._maybe_advance_commit_locked()
                        if self._next_index[peer_id] <= self._last_log()[0]:
                            ev.set()  # more to send
                    duck = self._lame_ducks.get(peer_id)
                    if duck is not None and (
                        commit >= duck[0]
                        and self._match_index.get(peer_id, 0) >= duck[0]
                    ):
                        # the removed peer has stored the removal entry
                        # AND seen a commit index covering it — it will
                        # apply its own removal; drain complete
                        self._finalize_removed_peer_locked(peer_id)
                        return
                else:
                    conflict = resp.get("conflict_index") or max(
                        1, self._next_index[peer_id] - 1
                    )
                    self._next_index[peer_id] = max(1, min(
                        conflict, self._next_index[peer_id] - 1,
                    ))
                    ev.set()

    def _build_batch_locked(self, next_idx: int):
        """Returns (entries, prev_index, prev_term, ok). ok=False when the
        prev entry has been compacted away (snapshot needed)."""
        last = self.log.last_index()
        prev_index = next_idx - 1
        prev_term = self._term_at(prev_index)
        if prev_term is None:
            return [], 0, 0, False
        batch = []
        for i in range(next_idx, min(last, next_idx + MAX_BATCH_ENTRIES - 1) + 1):
            try:
                e_term, e_type, e_data = self.log.get(i)
            except KeyError:
                break
            batch.append((i, e_term, e_type, e_data))
        return batch, prev_index, prev_term, True

    def _maybe_advance_commit_locked(self) -> None:
        if self.state != LEADER:
            return
        last, _ = self._last_log()
        # lame-duck (removed) peers may still have match entries while
        # their removal entry drains to them — they are NOT voters
        matches = sorted(
            [
                m
                for p, m in self._match_index.items()
                if p in self.config.peers
            ]
            + [last],
            reverse=True,
        )
        majority_at = matches[len(self.config.peers) // 2]
        if majority_at > self.commit_index and (
            self._term_at(majority_at) == self.term
        ):
            self.commit_index = majority_at
            self._apply_cv.notify_all()

    def _send_snapshot(self, peer_id: str, term: int) -> None:
        """InstallSnapshot: ship the whole state snapshot (fsm.go Restore
        path; hashicorp/raft sends it chunked — ours fits one frame for the
        state sizes in scope)."""
        if self.snapshot_fn is None or not self.config.data_dir:
            return
        path = self._snap_path()
        if not os.path.exists(path):
            with self._mu:
                self._take_snapshot_locked()
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return
        with self._mu:
            snap_index, snap_term = self.snap_index, self.snap_term
            peers_now = dict(self.config.peers)
        try:
            resp = self._client(peer_id).call(
                "Raft.install_snapshot",
                {
                    "term": term,
                    "leader_id": self.config.node_id,
                    "last_included_index": snap_index,
                    "last_included_term": snap_term,
                    "data": blob,
                    # membership rides along: the compacted log may no
                    # longer carry the RAFT_REMOVE_PEER entries, so a
                    # bootstrapped follower must adopt the current voter
                    # set or it would revert to its stale startup config
                    "peers": peers_now,
                },
                timeout=max(self.config.rpc_timeout, 10.0),
            )
        except Exception:
            return
        with self._mu:
            if resp["term"] > self.term:
                self._step_down_locked(resp["term"])
                return
            self._match_index[peer_id] = max(
                self._match_index.get(peer_id, 0), snap_index
            )
            self._next_index[peer_id] = snap_index + 1

    # -- RPC handlers (any state) ------------------------------------------
    def _handle_request_vote(self, args: dict) -> dict:
        with self._mu:
            if self._stop.is_set():
                return {"term": self.term, "granted": False}
            if args["candidate_id"] not in self.config.peers:
                # a server removed from the configuration (that may not
                # know it yet) must not be able to disrupt the cluster:
                # refuse WITHOUT adopting its inflated term
                # (hashicorp/raft ignores RequestVote from non-members)
                return {"term": args["term"], "granted": False}
            if args["term"] < self.term:
                return {"term": self.term, "granted": False}
            if args["term"] > self.term:
                self._step_down_locked(args["term"])
            last_index, last_term = self._last_log()
            up_to_date = (args["last_log_term"], args["last_log_index"]) >= (
                last_term, last_index,
            )
            if up_to_date and self.voted_for in (None, args["candidate_id"]):
                self.voted_for = args["candidate_id"]
                self._persist_term_vote()
                self._last_contact = time.monotonic()
                return {"term": self.term, "granted": True}
            return {"term": self.term, "granted": False}

    def _handle_append_entries(self, args: dict) -> dict:
        with self._mu:
            if self._stop.is_set():
                return {"term": self.term, "success": False}
            if args["term"] < self.term:
                return {"term": self.term, "success": False}
            if args["term"] > self.term or self.state != FOLLOWER:
                self._step_down_locked(args["term"])
            self.leader = args["leader_id"]
            self._last_contact = time.monotonic()

            prev_index, prev_term = args["prev_log_index"], args["prev_log_term"]
            local_prev_term = self._term_at(prev_index)
            if prev_index < self.snap_index:
                # already subsumed by our snapshot: report what we have
                return {
                    "term": self.term, "success": False,
                    "conflict_index": self.snap_index + 1,
                }
            if local_prev_term is None:
                return {
                    "term": self.term, "success": False,
                    "conflict_index": self._last_log()[0] + 1,
                }
            if local_prev_term != prev_term:
                return {
                    "term": self.term, "success": False,
                    "conflict_index": max(self.snap_index + 1, prev_index),
                }
            for index, e_term, e_type, e_data in args["entries"]:
                existing = self._term_at(index)
                if existing is not None and existing != e_term:
                    self.log.truncate_suffix(index)
                    existing = None
                if existing is None:
                    if self.log.last_index() not in (index - 1, 0) and (
                        index != self.snap_index + 1
                    ):
                        # gap would violate contiguity — reject; leader backs up
                        return {
                            "term": self.term, "success": False,
                            "conflict_index": self._last_log()[0] + 1,
                        }
                    self.log.append(index, e_term, e_type, e_data)
            if args["entries"]:
                self.log.sync()
            last_new = args["entries"][-1][0] if args["entries"] else prev_index
            if args["leader_commit"] > self.commit_index:
                self.commit_index = min(args["leader_commit"], last_new)
                self._apply_cv.notify_all()
            return {"term": self.term, "success": True, "match_index": last_new}

    def _handle_install_snapshot(self, args: dict) -> dict:
        # _apply_serial makes the restore atomic w.r.t. the applier's
        # check-then-apply of individual log entries (lock order:
        # _apply_serial before _mu)
        with self._apply_serial, self._mu:
            if self._stop.is_set() or args["term"] < self.term:
                return {"term": self.term}
            if args["term"] > self.term or self.state != FOLLOWER:
                self._step_down_locked(args["term"])
            self.leader = args["leader_id"]
            self._last_contact = time.monotonic()
            idx = args["last_included_index"]
            if idx <= self.last_applied:
                return {"term": self.term}  # stale snapshot
            path = self._snap_path() if self.config.data_dir else None
            if path is None:
                import tempfile

                fd, path = tempfile.mkstemp(suffix=".snap")
                os.close(fd)
            # atomic: our log prefix may already be compacted behind the
            # previous snapshot, so tearing it on crash loses state
            from ..state.snapshot import atomic_write_bytes

            atomic_write_bytes(path, args["data"])
            self.restore_fn(path)
            self.snap_index = idx
            self.snap_term = args["last_included_term"]
            self._persist_snap_meta()
            peers = args.get("peers")
            if peers:
                # adopt the leader's voter set; peers that vanished join
                # the durable removed set so a restart (which re-derives
                # from static config minus removals) doesn't resurrect
                self._removed_peers |= set(self.config.peers) - set(peers)
                self.config.peers = dict(peers)
                if self.config.node_id not in self.config.peers:
                    self._removed = True
                self._persist_membership_locked()
            # discard the whole log: snapshot subsumes it
            self.log.truncate_suffix(1)
            self.last_applied = self.fsm.store.latest_index
            self.commit_index = max(self.commit_index, self.last_applied)
            return {"term": self.term}

    # -- apply loop --------------------------------------------------------
    def _applier(self) -> None:
        while not self._stop.is_set():
            with self._mu:
                while (
                    self.last_applied >= self.commit_index
                    and not self._stop.is_set()
                ):
                    self._apply_cv.wait(timeout=0.2)
                    if self._stop.is_set():
                        return
                start = self.last_applied + 1
                end = self.commit_index
                entries = []
                for i in range(start, end + 1):
                    try:
                        term, mtype, data = self.log.get(i)
                    except Exception:  # gone (compacted/closed at shutdown)
                        break
                    entries.append((i, mtype, data))
            for i, mtype, data in entries:
                # _apply_serial holds InstallSnapshot off for the duration
                # of one entry's check+apply+update: without it, a restore
                # could land between the staleness check and fsm.apply,
                # and the stale entry would be applied onto the restored
                # (newer) store.
                with self._apply_serial:
                    with self._mu:
                        # entries at or below last_applied/snap_index are
                        # already reflected in the restored store (and
                        # their log may be gone) — applying them again
                        # would regress the FSM
                        if i <= self.last_applied or i <= self.snap_index:
                            continue
                    # log entries can originate from the network
                    # (append_entries from any peer) — deserialize through
                    # the framework allowlist, not bare pickle
                    from ..rpc.framing import restricted_loads

                    payload = restricted_loads(data)
                    try:
                        result = self.fsm.apply(i, mtype, payload)
                        err = None
                    except Exception as e:  # noqa: BLE001 — surface to waiter
                        result, err = None, e
                    from ..server.fsm import MsgType

                    if mtype == int(MsgType.RAFT_REMOVE_PEER) and payload:
                        # membership change: committed, so every surviving
                        # replica applies the same config transition at
                        # the same log position
                        self._apply_remove_peer_config(
                            payload.get("node_id"), i
                        )
                    with self._mu:
                        self.last_applied = max(self.last_applied, i)
                        fut = self._futures.pop(i, None)
                        self._entries_since_snap += 1
                if fut is not None and not fut.done():
                    if err is not None:
                        fut.set_exception(err)
                    else:
                        fut.set_result(result)
            self._maybe_snapshot()

    def _apply_remove_peer_config(
        self, node_id: Optional[str], removal_index: int = 0
    ) -> None:
        """Config transition for a committed RAFT_REMOVE_PEER entry."""
        if not node_id:
            return
        with self._mu:
            if node_id == self.config.node_id:
                # we are the removed server: stop participating (no
                # elections; stale-term RPCs are answered but never won)
                self._removed = True
                self._removed_peers.add(node_id)
                self._persist_membership_locked()
                if self.state == LEADER:
                    self._step_down_locked(self.term)
                else:
                    self.state = FOLLOWER
                log.info("raft: this server (%s) removed from the "
                         "configuration", node_id)
                return
            if node_id not in self.config.peers:
                return
            del self.config.peers[node_id]
            self._removed_peers.add(node_id)
            self._persist_membership_locked()
            if self.state == LEADER:
                # lame-duck: keep replicating the removal entry to the
                # (possibly live) removed peer so it learns and stops
                # electing; the loop finalizes on ack or deadline
                self._lame_ducks[node_id] = (
                    removal_index, time.monotonic() + 5.0
                )
                ev = self._repl_events.get(node_id)
                if ev is not None:
                    ev.set()
            else:
                self._match_index.pop(node_id, None)
                self._next_index.pop(node_id, None)
            log.info("raft: removed peer %s; %d voters remain",
                     node_id, len(self.config.peers))
            # quorum shrank — entries may now be committed
            self._maybe_advance_commit_locked()

    def _finalize_removed_peer_locked(self, node_id: str) -> None:
        """Drop the replication machinery for a removed peer once its
        lame-duck window closes (ack of the removal entry or timeout)."""
        self._lame_ducks.pop(node_id, None)
        self._match_index.pop(node_id, None)
        self._next_index.pop(node_id, None)
        self._repl_events.pop(node_id, None)
        client = self._clients.pop(node_id, None)
        if client is not None:
            # close outside _mu is ideal, but close() only shuts a socket
            threading.Thread(target=client.close, daemon=True).start()

    def _maybe_snapshot(self) -> None:
        if (
            self.snapshot_fn is None
            or not self.config.data_dir
            or self._entries_since_snap < self.config.snapshot_threshold
        ):
            return
        with self._mu:
            self._take_snapshot_locked()

    def _take_snapshot_locked(self) -> None:
        index = self.last_applied
        if index == 0:
            return
        term = self._term_at(index) or self.snap_term
        self.snapshot_fn(self._snap_path())
        self.snap_index = index
        self.snap_term = term
        self._persist_snap_meta()
        self.log.compact_prefix(index)
        self.log.sync()
        self._entries_since_snap = 0

    def snapshot(self) -> int:
        with self._mu:
            self._take_snapshot_locked()
            return self.snap_index
