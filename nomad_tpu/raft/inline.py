"""InlineRaft — the single-server raft seam.

The dev-agent path (reference: a single-server Raft cluster that elects
itself instantly; nomad agent -dev). Writes are serialized, optionally
made durable in the native WAL, and applied to the FSM immediately. On
boot, the newest snapshot is restored and the log suffix replayed —
checkpoint/resume for the whole control plane (fsm.go Snapshot/Restore).
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Optional, Tuple

SNAP_EVERY_ENTRIES = 4096  # log entries between automatic snapshots


class InlineRaft:
    def __init__(self, fsm, data_dir: Optional[str] = None,
                 snapshot_fn=None, restore_fn=None):
        """``snapshot_fn(path) -> index`` / ``restore_fn(path) -> store``
        hook the state-store snapshot machinery (state/snapshot.py)."""
        self.fsm = fsm
        self.data_dir = data_dir
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self._lock = threading.Lock()
        self._wal = None
        self._applied_since_snap = 0
        if data_dir:
            from ..native import WalStore

            os.makedirs(data_dir, exist_ok=True)
            self._wal = WalStore(os.path.join(data_dir, "raft"))

    # -- contract ----------------------------------------------------------
    def is_leader(self) -> bool:
        return True

    def leader_id(self) -> Optional[str]:
        return "local"

    def peers(self) -> dict:
        return {"local": "local"}

    def remove_peer(self, node_id: str, timeout: float = 10.0) -> None:
        raise ValueError("single-server (dev) mode has no removable peers")

    def apply(self, mtype: int, payload: Optional[dict] = None,
              timeout: float = 10.0) -> Tuple[int, Any]:
        from ..chaos.plane import chaos_site, make_fault

        # consulted before the entry is assigned an index: a "drop"
        # rejects the write to the caller (a lost raft commit, like a
        # leadership change mid-apply) — nothing is applied, nothing is
        # durable, and the caller's retry path must cope
        if chaos_site("fsm.apply") == "drop":
            raise make_fault("fsm.apply")
        with self._lock:
            index = self.fsm.store.latest_index + 1
            if self._wal is not None:
                self._wal.append(
                    index, term=1, type_=int(mtype),
                    data=pickle.dumps(payload, pickle.HIGHEST_PROTOCOL),
                )
                # Durable-dev-agent contract: the write is acked to the
                # caller, so it must survive power loss, not just
                # crash-stop. fsync per apply (group-committed under the
                # serializing lock).
                self._wal.sync()
            result = self.fsm.apply(index, mtype, payload)
            if self._wal is not None:
                self._applied_since_snap += 1
                if (
                    self.snapshot_fn is not None
                    and self._applied_since_snap >= SNAP_EVERY_ENTRIES
                ):
                    self._snapshot_locked()
            return index, result

    def barrier(self, timeout: float = 10.0) -> int:
        from ..server.fsm import MsgType

        index, _ = self.apply(MsgType.NOOP, None, timeout=timeout)
        return index

    # -- durability --------------------------------------------------------
    def _snap_path(self) -> str:
        return os.path.join(self.data_dir, "state.snap")

    def _snapshot_locked(self) -> None:
        index = self.snapshot_fn(self._snap_path())
        self._wal.compact_prefix(index)
        self._wal.sync()
        self._applied_since_snap = 0

    def snapshot(self) -> int:
        """Explicit checkpoint (operator snapshot save)."""
        with self._lock:
            if self._wal is None or self.snapshot_fn is None:
                raise RuntimeError("snapshots require a data_dir")
            self._snapshot_locked()
            return self._wal.last_index() or self.fsm.store.latest_index

    def restore(self) -> bool:
        """Boot-time recovery: restore snapshot (if any), replay the log
        suffix. Returns True when any durable state was recovered."""
        if self._wal is None:
            return False
        recovered = False
        if self.restore_fn is not None and os.path.exists(self._snap_path()):
            self.restore_fn(self._snap_path())
            recovered = True
        first, last = self._wal.first_index(), self._wal.last_index()
        start = max(first, self.fsm.store.latest_index + 1)
        for index in range(start, last + 1):
            _term, mtype, data = self._wal.get(index)
            self.fsm.apply(index, mtype, pickle.loads(data))
            recovered = True
        return recovered

    def sync(self) -> None:
        if self._wal is not None:
            self._wal.sync()

    def close(self) -> None:
        if self._wal is not None:
            self._wal.sync()
            self._wal.close()
            self._wal = None

    def stats(self) -> dict:
        return {
            "state": "Leader",
            "term": 1,
            "last_log_index": (
                self._wal.last_index() if self._wal else self.fsm.store.latest_index
            ),
            "commit_index": self.fsm.store.latest_index,
            "num_peers": 0,
        }
