"""Wire framing: u32 big-endian length prefix + serialized message dict.

Messages:
  request   {"seq": int, "method": str, "args": Any}
  response  {"seq": int, "result": Any}           (unary)
  error     {"seq": int, "error": str}
  chunk     {"seq": int, "chunk": Any, "more": bool}   (streaming)

Serialization is pickle restricted on the *receive* side: ``recv_frame``
resolves globals through an allowlist (framework dataclasses/enums, a few
stdlib containers, numpy array reconstruction) so a crafted frame from an
untrusted peer cannot reach arbitrary callables — the classic
pickle-deserialization RCE. The reference's wire format is msgpack over
TLS/mTLS (nomad/rpc.go); here the codec restriction plus optional HMAC
transport auth (below) covers the same trust boundary for cluster peers.

Transport auth: when a cluster secret is configured (``set_rpc_secret`` or
the NOMAD_TPU_RPC_SECRET env var), every frame carries an HMAC-SHA256 tag
over direction byte + payload, and unauthenticated frames are rejected
before deserialization. Scope of the guarantee: the MAC authenticates
*cluster membership* (only secret holders can produce acceptable frames)
and direction (a server frame cannot be reflected back as a request); it
does NOT provide per-frame freshness — a captured frame can be replayed
verbatim by an on-path attacker until the secret rotates. Deployments
needing replay protection should run the RPC ports over a trusted network
or a TLS tunnel, as the reference does (nomad/rpc.go TLS wrap).

The 64 MB frame cap matches the WAL's record cap; anything larger is a
protocol violation, not data.
"""

from __future__ import annotations

import hashlib
import hmac
import importlib
import io
import os
import pickle
import socket
import struct
from typing import Any, Optional

MAX_FRAME = 64 << 20
_LEN = struct.Struct(">I")
_TAG_LEN = hashlib.sha256().digest_size

_FLAG_PLAIN = 0
_FLAG_HMAC = 1  # bit 0: authenticated
_FLAG_DIR_S = 2  # bit 1: direction server→client (0 = client→server)

_secret: Optional[bytes] = None
_secret_loaded = False


def set_rpc_secret(secret: Optional[bytes | str]) -> None:
    """Configure the cluster transport secret (all peers must agree)."""
    global _secret, _secret_loaded
    if isinstance(secret, str):
        secret = secret.encode()
    _secret = secret or None
    _secret_loaded = True


def _get_secret() -> Optional[bytes]:
    global _secret, _secret_loaded
    if not _secret_loaded:
        env = os.environ.get("NOMAD_TPU_RPC_SECRET")
        _secret = env.encode() if env else None
        _secret_loaded = True
    return _secret


class FramingError(Exception):
    pass


# -- restricted deserialization ----------------------------------------------

# Modules whose classes may cross the wire. A fixed set — find_class must
# not import attacker-named modules (side-effectful imports, e.g. anything
# that pulls in jax, can hang or latch process state).
_SAFE_MODULES = frozenset(
    {
        "nomad_tpu.structs",
        "nomad_tpu.structs.job",
        "nomad_tpu.structs.node",
        "nomad_tpu.structs.alloc",
        "nomad_tpu.structs.evaluation",
        "nomad_tpu.structs.plan",
        "nomad_tpu.structs.resources",
        "nomad_tpu.structs.network",
        "nomad_tpu.structs.volumes",
        "nomad_tpu.structs.deployment",
        "nomad_tpu.state.store",
        "nomad_tpu.acl.tokens",
        "nomad_tpu.acl.policy",
    }
)

_SAFE_GLOBALS = {
    ("builtins", "set"),
    ("builtins", "frozenset"),
    ("builtins", "bytearray"),
    ("builtins", "complex"),
    ("builtins", "slice"),
    ("builtins", "range"),
    ("collections", "OrderedDict"),
    ("collections", "deque"),
    ("datetime", "datetime"),
    ("datetime", "date"),
    ("datetime", "time"),
    ("datetime", "timedelta"),
    ("datetime", "timezone"),
    # numpy array reconstruction (structs.resources carries ndarrays)
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy._core.numeric", "_frombuffer"),
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        if (module, name) in _SAFE_GLOBALS:
            return super().find_class(module, name)
        # Framework types: classes from the fixed struct-module set only.
        # NOTE the actual invariant: functions never resolve, but the
        # allowlisted CLASSES remain callable with attacker-chosen args —
        # pickle's REDUCE opcode invokes cls(*args), running __init__.
        # Safety therefore rests on every allowlisted class being a
        # side-effect-free data class (keep it that way when extending
        # _SAFE_MODULES; a class whose __init__ touches files/sockets/
        # subprocesses would reopen a gadget).
        if module in _SAFE_MODULES:
            try:
                mod = importlib.import_module(module)
            except Exception as e:  # noqa: BLE001 — error contract
                raise FramingError(f"cannot resolve RPC global module: {module}") from e
            obj = getattr(mod, name, None)
            if isinstance(obj, type) and obj.__module__ == module:
                return obj
        raise FramingError(f"disallowed global in RPC frame: {module}.{name}")


def restricted_loads(payload: bytes) -> Any:
    """Deserialize with the framework allowlist — for any bytes whose
    producer is not fully trusted (RPC frames, replicated log entries)."""
    try:
        return _RestrictedUnpickler(io.BytesIO(payload)).load()
    except FramingError:
        raise
    except Exception as e:  # torn/corrupt pickle must not crash callers
        raise FramingError(f"malformed frame payload: {e}") from e


# -- framing -----------------------------------------------------------------


def send_frame(sock: socket.socket, msg: dict, *, server_side: bool = False) -> None:
    """``server_side`` marks the frame's direction (server→client); the
    direction byte is covered by the MAC so a captured server frame cannot
    be reflected back at the server as a request."""
    payload = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    if len(payload) > MAX_FRAME:
        raise FramingError(f"frame too large: {len(payload)}")
    flag = _FLAG_DIR_S if server_side else 0
    secret = _get_secret()
    if secret is not None:
        flag |= _FLAG_HMAC
        tag = hmac.new(secret, bytes([flag]) + payload, hashlib.sha256).digest()
        header = _LEN.pack(len(payload) + 1 + _TAG_LEN) + bytes([flag])
        sock.sendall(header + tag + payload)
    else:
        sock.sendall(_LEN.pack(len(payload) + 1) + bytes([flag]) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket, *, expect_server: bool | None = None) -> Any:
    """``expect_server`` asserts the authenticated frame's direction:
    True = must come from a server, False = must come from a client,
    None = either (direction unchecked)."""
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    if n > MAX_FRAME + 1 + _TAG_LEN or n < 1:
        raise FramingError(f"bad frame length: {n}")
    body = _recv_exact(sock, n)
    flag, body = body[0], body[1:]
    if flag & ~(_FLAG_HMAC | _FLAG_DIR_S):
        raise FramingError(f"unknown frame flag: {flag}")
    secret = _get_secret()
    if secret is not None:
        if not flag & _FLAG_HMAC or len(body) < _TAG_LEN:
            raise FramingError("unauthenticated frame rejected")
        tag, payload = body[:_TAG_LEN], body[_TAG_LEN:]
        if not hmac.compare_digest(
            tag, hmac.new(secret, bytes([flag]) + payload, hashlib.sha256).digest()
        ):
            raise FramingError("frame HMAC mismatch")
        if expect_server is not None and bool(flag & _FLAG_DIR_S) != expect_server:
            raise FramingError("frame direction mismatch (reflected frame?)")
    else:
        if flag & _FLAG_HMAC:
            if len(body) < _TAG_LEN:
                raise FramingError("truncated authenticated frame")
            payload = body[_TAG_LEN:]  # peer signs, we don't require it
        else:
            payload = body
    return restricted_loads(payload)
