"""Wire framing: u32 big-endian length prefix + pickled message dict.

Messages:
  request   {"seq": int, "method": str, "args": Any}
  response  {"seq": int, "result": Any}           (unary)
  error     {"seq": int, "error": str}
  chunk     {"seq": int, "chunk": Any, "more": bool}   (streaming)

The 64 MB frame cap matches the WAL's record cap; anything larger is a
protocol violation, not data.
"""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Any

MAX_FRAME = 64 << 20
_LEN = struct.Struct(">I")


class FramingError(Exception):
    pass


def send_frame(sock: socket.socket, msg: dict) -> None:
    payload = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    if len(payload) > MAX_FRAME:
        raise FramingError(f"frame too large: {len(payload)}")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    if n > MAX_FRAME:
        raise FramingError(f"frame too large: {n}")
    return pickle.loads(_recv_exact(sock, n))
