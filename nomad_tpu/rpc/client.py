"""RPC client: one multiplexed connection per remote address with a demux
reader thread; blocking unary calls and streaming iterators.

Reference: helper/pool (ConnPool — the server-to-server connection pool,
nomad/rpc.go uses it for forwarding) and client/rpc.go (client→server
calls with retry/rebalance on connection failure).
"""

from __future__ import annotations

import itertools
import logging
import queue
import socket
import threading
from typing import Any, Iterator, Optional

from .framing import FramingError, recv_frame, send_frame


class RPCError(Exception):
    """Error raised by the remote handler (crossed the wire)."""


class _Conn:
    def __init__(self, address: str, timeout: float):
        host, port = address.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)), timeout=timeout)
        # TCP self-connect guard: dialing a free port can land on a socket
        # whose ephemeral local port equals the target, yielding a
        # connection to ourselves that then squats the server's port.
        if self.sock.getsockname() == self.sock.getpeername():
            self.sock.close()
            raise ConnectionError(f"self-connect dialing {address}")
        self.sock.settimeout(None)  # reader blocks; callers time out on queues
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.send_lock = threading.Lock()
        self.pending: dict[int, queue.Queue] = {}
        self.pending_lock = threading.Lock()
        self.dead = threading.Event()
        self.reader = threading.Thread(
            target=self._read_loop, name="rpc-demux", daemon=True
        )
        self.reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                msg = recv_frame(self.sock, expect_server=True)
                with self.pending_lock:
                    q = self.pending.get(msg.get("seq"))
                if q is not None:
                    q.put(msg)
        except FramingError as e:
            # protocol violation (bad auth, disallowed global, torn frame):
            # drop the connection — callers see "connection closed"
            logging.getLogger(__name__).warning("rpc: protocol violation: %s", e)
        except (ConnectionError, OSError):
            pass
        finally:
            self.dead.set()
            with self.pending_lock:
                for q in self.pending.values():
                    q.put({"error": "connection closed"})
                self.pending.clear()
            try:
                self.sock.close()
            except OSError:
                pass

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class RPCClient:
    def __init__(self, address: str, timeout: float = 10.0):
        self.address = address
        self.timeout = timeout
        self._seq = itertools.count(1)
        self._conn: Optional[_Conn] = None
        self._conn_lock = threading.Lock()

    def _get_conn(self) -> _Conn:
        with self._conn_lock:
            if self._conn is None or self._conn.dead.is_set():
                self._conn = _Conn(self.address, self.timeout)
            return self._conn

    def close(self) -> None:
        with self._conn_lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None

    def _send(self, method: str, args: Any) -> tuple[_Conn, int, queue.Queue]:
        conn = self._get_conn()
        seq = next(self._seq)
        q: queue.Queue = queue.Queue()
        with conn.pending_lock:
            conn.pending[seq] = q
        try:
            with conn.send_lock:
                send_frame(conn.sock, {"seq": seq, "method": method, "args": args})
        except (ConnectionError, OSError) as e:
            with conn.pending_lock:
                conn.pending.pop(seq, None)
            conn.dead.set()
            raise ConnectionError(f"rpc send to {self.address}: {e}") from e
        return conn, seq, q

    def call(self, method: str, args: Any = None,
             timeout: Optional[float] = None) -> Any:
        conn, seq, q = self._send(method, args)
        try:
            msg = q.get(timeout=timeout if timeout is not None else self.timeout)
        except queue.Empty:
            raise TimeoutError(f"rpc {method} to {self.address} timed out") from None
        finally:
            with conn.pending_lock:
                conn.pending.pop(seq, None)
        if "error" in msg:
            if msg["error"] == "connection closed":
                raise ConnectionError(f"rpc {method}: connection closed")
            raise RPCError(msg["error"])
        return msg.get("result")

    def stream(self, method: str, args: Any = None,
               timeout: Optional[float] = None) -> Iterator[Any]:
        """Iterate streamed chunks until the server marks the end."""
        conn, seq, q = self._send(method, args)
        per_chunk = timeout if timeout is not None else self.timeout
        try:
            while True:
                try:
                    msg = q.get(timeout=per_chunk)
                except queue.Empty:
                    raise TimeoutError(
                        f"rpc stream {method} to {self.address} timed out"
                    ) from None
                if "error" in msg:
                    if msg["error"] == "connection closed":
                        raise ConnectionError(f"rpc stream {method}: closed")
                    raise RPCError(msg["error"])
                if not msg.get("more", False):
                    return
                yield msg.get("chunk")
        finally:
            with conn.pending_lock:
                conn.pending.pop(seq, None)
