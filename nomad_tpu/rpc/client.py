"""RPC client: one multiplexed connection per remote address with a demux
reader thread; blocking unary calls and streaming iterators, with bounded
retry + exponential backoff + seeded jitter on connection failure.

Retry is idempotency-aware. A *dial* failure (the connection could not
be established, so nothing reached the server) is retried for every
method. Once a request frame may have left the socket — a send error or
a connection that died before the reply — only methods registered as
idempotent (:data:`DEFAULT_IDEMPOTENT` plus :meth:`RPCClient.mark_idempotent`)
are retried; everything else, plan/job submission above all, stays
at-most-once and surfaces the ``ConnectionError`` to the caller.
Admission throttling is the exception: a :class:`RPCThrottled` reply
means the server refused the request before executing it, so every
method retries, sleeping the server's ``Retry-After`` hint (or the
normal backoff if longer).

Reference: helper/pool (ConnPool — the server-to-server connection pool,
nomad/rpc.go uses it for forwarding) and client/rpc.go (client→server
calls with retry/rebalance on connection failure; server-list rebalance
lives one layer up in ``server/cluster.py`` RemoteClientRPC).
"""

from __future__ import annotations

import itertools
import logging
import queue
import random
import socket
import threading
import time
from typing import Any, Callable, Iterator, Optional

from .framing import FramingError, recv_frame, send_frame

#: Methods safe to retry after the request may have reached the server:
#: reads, anti-entropy merges, and TTL touches. Raft RPCs are duplicate-
#: safe by protocol but keep their own retry cadence (election timing),
#: and all write forwarding (job/plan submission) is at-most-once.
DEFAULT_IDEMPOTENT = frozenset({
    "Nomad.heartbeat",
    "Nomad.pull_allocs",
    "Nomad.gossip_sync",
    "FS.list",
    "FS.stat",
    "FS.read",
    "FS.logs",
})


class RPCError(Exception):
    """Error raised by the remote handler (crossed the wire)."""


class RPCThrottled(RPCError):
    """Remote admission control refused the request (429-equivalent).

    Carries the server's ``Retry-After`` hint in seconds. A throttled
    request was rejected BEFORE execution, so retrying it is safe for
    every method — idempotent or not — and the client honors the hint
    in its backoff."""

    def __init__(self, message: str, retry_after: float):
        super().__init__(message)
        self.retry_after = float(retry_after)


class _Conn:
    def __init__(self, address: str, timeout: float):
        host, port = address.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)), timeout=timeout)
        # TCP self-connect guard: dialing a free port can land on a socket
        # whose ephemeral local port equals the target, yielding a
        # connection to ourselves that then squats the server's port.
        if self.sock.getsockname() == self.sock.getpeername():
            self.sock.close()
            raise ConnectionError(f"self-connect dialing {address}")
        self.sock.settimeout(None)  # reader blocks; callers time out on queues
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.send_lock = threading.Lock()
        self.pending: dict[int, queue.Queue] = {}
        self.pending_lock = threading.Lock()
        self.dead = threading.Event()
        self.reader = threading.Thread(
            target=self._read_loop, name="rpc-demux", daemon=True
        )
        self.reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                msg = recv_frame(self.sock, expect_server=True)
                with self.pending_lock:
                    q = self.pending.get(msg.get("seq"))
                if q is not None:
                    q.put(msg)
        except FramingError as e:
            # protocol violation (bad auth, disallowed global, torn frame):
            # drop the connection — callers see "connection closed"
            logging.getLogger(__name__).warning("rpc: protocol violation: %s", e)
        except (ConnectionError, OSError):
            pass
        finally:
            self.dead.set()
            with self.pending_lock:
                for q in self.pending.values():
                    q.put({"error": "connection closed"})
                self.pending.clear()
            try:
                self.sock.close()
            except OSError:
                pass

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class RPCClient:
    def __init__(
        self,
        address: str,
        timeout: float = 10.0,
        max_attempts: int = 3,
        backoff_base: float = 0.05,
        backoff_cap: float = 2.0,
        idempotent: tuple[str, ...] = (),
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.address = address
        self.timeout = timeout
        self.max_attempts = max(1, max_attempts)
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._idempotent: set[str] = set(DEFAULT_IDEMPOTENT) | set(idempotent)
        self._sleep = sleep
        # seeded jitter: retry timing is a function of the target, not
        # of process entropy — chaos runs stay reproducible
        self._rng = random.Random(f"rpc-retry:{address}")
        self._seq = itertools.count(1)
        self._conn: Optional[_Conn] = None
        self._conn_lock = threading.Lock()

    def mark_idempotent(self, *methods: str) -> None:
        """Register methods as safe to retry after a possible send."""
        self._idempotent.update(methods)

    def is_idempotent(self, method: str) -> bool:
        return method in self._idempotent

    def _get_conn(self) -> _Conn:
        with self._conn_lock:
            if self._conn is None or self._conn.dead.is_set():
                self._conn = _Conn(self.address, self.timeout)
            return self._conn

    def close(self) -> None:
        with self._conn_lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None

    def _retry_sleep(self, method: str, attempt: int) -> None:
        from ..utils.metrics import global_metrics

        global_metrics.incr("nomad.resilience.rpc.retries")
        delay = min(
            self.backoff_cap, self.backoff_base * (2.0 ** (attempt - 1))
        )
        self._sleep(delay * self._rng.uniform(0.5, 1.5))

    def _throttle_sleep(self, retry_after: float, attempt: int) -> None:
        from ..utils.metrics import global_metrics

        global_metrics.incr("nomad.admission.rpc_throttled_retries")
        backoff = min(
            self.backoff_cap, self.backoff_base * (2.0 ** (attempt - 1))
        )
        self._sleep(max(float(retry_after), backoff) * self._rng.uniform(1.0, 1.25))

    def _send(
        self, conn: _Conn, method: str, args: Any
    ) -> tuple[_Conn, int, queue.Queue]:
        seq = next(self._seq)
        q: queue.Queue = queue.Queue()
        with conn.pending_lock:
            conn.pending[seq] = q
        try:
            with conn.send_lock:
                send_frame(conn.sock, {"seq": seq, "method": method, "args": args})
        except (ConnectionError, OSError) as e:
            with conn.pending_lock:
                conn.pending.pop(seq, None)
            conn.dead.set()
            raise ConnectionError(f"rpc send to {self.address}: {e}") from e
        from ..chaos.plane import chaos_site

        # the frame has left the socket: a drop here models the network
        # yanking the connection after the server may have processed the
        # request — exactly the window where idempotency matters
        if chaos_site("rpc.conn_drop") == "drop":
            conn.close()
            # the response is lost even if the kernel already buffered
            # it — discard any raced-in reply so the fault is
            # deterministic regardless of scheduler timing; mark the
            # conn dead now so the retry dials fresh instead of racing
            # the reader thread's own dead.set()
            conn.dead.set()
            with conn.pending_lock:
                conn.pending.pop(seq, None)
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            q.put({"error": "connection closed"})
        return conn, seq, q

    def _call_once(
        self, conn: _Conn, method: str, args: Any, timeout: Optional[float]
    ) -> Any:
        conn, seq, q = self._send(conn, method, args)
        try:
            msg = q.get(timeout=timeout if timeout is not None else self.timeout)
        except queue.Empty:
            raise TimeoutError(f"rpc {method} to {self.address} timed out") from None
        finally:
            with conn.pending_lock:
                conn.pending.pop(seq, None)
        if "error" in msg:
            if msg["error"] == "connection closed":
                raise ConnectionError(f"rpc {method}: connection closed")
            if "retry_after" in msg:
                raise RPCThrottled(msg["error"], msg["retry_after"])
            raise RPCError(msg["error"])
        return msg.get("result")

    def call(self, method: str, args: Any = None,
             timeout: Optional[float] = None) -> Any:
        attempt = 0
        while True:
            try:
                conn = self._get_conn()
            except OSError as e:
                # dial failure: nothing reached the server, every method
                # is safe to retry
                attempt += 1
                if attempt >= self.max_attempts:
                    raise ConnectionError(
                        f"rpc dial {self.address}: {e}"
                    ) from e
                self._retry_sleep(method, attempt)
                continue
            try:
                return self._call_once(conn, method, args, timeout)
            except RPCThrottled as e:
                # server-side admission refusal: the request never
                # executed, so EVERY method retries — honoring the
                # server's Retry-After over our own backoff when it's
                # longer (jittered so a shed wave doesn't resync)
                attempt += 1
                if attempt >= self.max_attempts:
                    raise
                self._throttle_sleep(e.retry_after, attempt)
            except ConnectionError:
                # the request may have executed remotely: at-most-once
                # unless the method is registered idempotent
                attempt += 1
                if (
                    method not in self._idempotent
                    or attempt >= self.max_attempts
                ):
                    raise
                self._retry_sleep(method, attempt)

    def stream(self, method: str, args: Any = None,
               timeout: Optional[float] = None) -> Iterator[Any]:
        """Iterate streamed chunks until the server marks the end.
        Dial failures retry like :meth:`call`; once a chunk has been
        yielded a dead connection is surfaced, never re-spliced."""
        per_chunk = timeout if timeout is not None else self.timeout
        attempt = 0
        yielded = False
        while True:
            try:
                conn = self._get_conn()
            except OSError as e:
                attempt += 1
                if attempt >= self.max_attempts:
                    raise ConnectionError(
                        f"rpc dial {self.address}: {e}"
                    ) from e
                self._retry_sleep(method, attempt)
                continue
            try:
                conn, seq, q = self._send(conn, method, args)
            except ConnectionError:
                attempt += 1
                if (
                    method not in self._idempotent
                    or attempt >= self.max_attempts
                ):
                    raise
                self._retry_sleep(method, attempt)
                continue
            try:
                while True:
                    try:
                        msg = q.get(timeout=per_chunk)
                    except queue.Empty:
                        raise TimeoutError(
                            f"rpc stream {method} to {self.address} timed out"
                        ) from None
                    if "error" in msg:
                        if msg["error"] == "connection closed":
                            if (
                                not yielded
                                and method in self._idempotent
                                and attempt + 1 < self.max_attempts
                            ):
                                break  # restart the stream from scratch
                            raise ConnectionError(
                                f"rpc stream {method}: closed"
                            )
                        raise RPCError(msg["error"])
                    if not msg.get("more", False):
                        return
                    yielded = True
                    yield msg.get("chunk")
            finally:
                with conn.pending_lock:
                    conn.pending.pop(seq, None)
            attempt += 1
            self._retry_sleep(method, attempt)
