"""RPC server: TCP listener, per-connection reader, per-request worker
threads, streaming generator support.

Reference: nomad/rpc.go handleConn (:195) / handleNomadConn, and the
streaming registry (structs.StreamingRpcRegistry, nomad/server.go:158).
A connection carries many concurrent requests distinguished by ``seq`` —
the role yamux streams play in the reference.
"""

from __future__ import annotations

import logging
import socket
import threading
from typing import Any, Callable, Dict, Iterator, Optional

from .framing import FramingError, recv_frame, send_frame

log = logging.getLogger(__name__)


class RPCServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: Dict[str, Callable[[Any], Any]] = {}
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()

    def register(self, method: str, handler: Callable[[Any], Any]) -> None:
        """Handler returns a value (unary) or an iterator (streaming)."""
        self._handlers[method] = handler

    def register_all(self, prefix: str, obj: object) -> None:
        """Register every public method of ``obj`` as ``prefix.name`` —
        the endpoint-registration analog of nomad/server.go:262-289."""
        for name in dir(obj):
            if name.startswith("_"):
                continue
            fn = getattr(obj, name)
            if callable(fn):
                self.register(f"{prefix}.{name}", fn)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(128)
        # a blocking accept() is not reliably woken by close() from another
        # thread on Linux, and the zombie listener would squat the port;
        # poll so the accept thread notices _stop and releases the socket
        self._sock.settimeout(0.25)
        t = threading.Thread(target=self._accept_loop, name="rpc-accept",
                             daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(None)  # accepted sockets must block normally
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(conn)
            t = threading.Thread(
                target=self._conn_loop, args=(conn,), name="rpc-conn",
                daemon=True,
            )
            t.start()

    def _conn_loop(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()  # interleave whole frames only
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_frame(conn, expect_server=False)
                except FramingError as e:
                    log.warning("rpc: protocol violation from peer: %s", e)
                    return
                except (ConnectionError, OSError):
                    return
                threading.Thread(
                    target=self._dispatch,
                    args=(conn, send_lock, msg),
                    daemon=True,
                ).start()
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn, send_lock, msg) -> None:
        seq = msg.get("seq")
        method = msg.get("method", "")
        handler = self._handlers.get(method)

        def reply(payload: dict) -> None:
            payload["seq"] = seq
            with send_lock:
                send_frame(conn, payload, server_side=True)

        if handler is None:
            try:
                reply({"error": f"unknown method {method!r}"})
            except OSError:
                pass
            return
        try:
            result = handler(msg.get("args"))
            if isinstance(result, Iterator):
                for chunk in result:
                    reply({"chunk": chunk, "more": True})
                reply({"chunk": None, "more": False})
            else:
                reply({"result": result})
        except (ConnectionError, OSError):
            pass  # peer went away mid-reply
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            log.debug("rpc handler %s failed", method, exc_info=True)
            payload = {"error": f"{type(e).__name__}: {e}"}
            # admission throttling (server/admission.py AdmissionRejected
            # or anything else carrying retry_after): ship the hint so
            # the client can honor it in its backoff
            retry_after = getattr(e, "retry_after", None)
            if retry_after is not None:
                payload["retry_after"] = float(retry_after)
            try:
                reply(payload)
            except OSError:
                pass
