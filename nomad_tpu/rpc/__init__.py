"""Internal RPC — the framework's server↔server / client↔server transport.

Reference: nomad/rpc.go (msgpack-RPC multiplexed over yamux :24-30,
handleConn :195), helper/pool (server-to-server connection pool), and the
streaming-RPC registry (nomad/server.go:158). Here: length-prefixed frames
over TCP with sequence-id multiplexing (many in-flight calls per
connection — the yamux role), thread-per-request dispatch, and streaming
responses for logs/exec/event feeds.

Payloads are pickled Python structs — the fidelity analog of the
reference's msgpack codec on its trusted server mesh; TLS/mTLS wrapping is
the same boundary the reference uses (tlsutil) and slots in at the socket
layer.
"""

from .client import RPCClient, RPCError
from .server import RPCServer

__all__ = ["RPCClient", "RPCServer", "RPCError"]
