// walstore — segmented append-only write-ahead log + tiny durable KV.
//
// The native durable-state layer of the framework: the role raft-boltdb
// (Raft log + stable store) and BoltDB (client state, helper/boltdd) play
// in the reference (nomad/server.go:105-109 raft wiring; client/state/).
// The reference gets native-speed durability from C-backed Go libraries;
// here it is a first-class C++ component bound into Python via ctypes
// (no pybind11 in the image).
//
// Layout on disk (one directory per store):
//   <dir>/00000000000000000001.seg   segment named by first index it holds
//   <dir>/meta.kv                    atomic whole-file KV (term/vote/...)
//
// Record framing (little-endian, per entry):
//   u32 crc32   — over the header bytes after crc + payload
//   u32 len     — payload length
//   u64 index   — monotonically increasing log index
//   u64 term    — raft term (0 when unused)
//   u32 type    — application record type
//   u8  payload[len]
//
// Torn tails (crash mid-append) are detected by CRC/short-read on open and
// truncated away. Suffix truncation (raft conflict resolution) and prefix
// compaction (post-snapshot) are supported; compaction drops whole
// segments only, mirroring segment-granular log stores.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cerrno>
#include <string>
#include <vector>
#include <map>
#include <mutex>
#include <algorithm>

#include <dirent.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

// ---- crc32 (IEEE, table-driven) ----
uint32_t crc_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
  }
} crc_init_;

uint32_t crc32(const uint8_t* buf, size_t len, uint32_t crc = 0) {
  crc = ~crc;
  for (size_t i = 0; i < len; i++) crc = crc_table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

#pragma pack(push, 1)
struct RecHeader {
  uint32_t crc;
  uint32_t len;
  uint64_t index;
  uint64_t term;
  uint32_t type;
};
#pragma pack(pop)
static_assert(sizeof(RecHeader) == 28, "header packing");

struct EntryLoc {
  uint32_t segment;  // index into segments vector
  uint64_t offset;   // file offset of the record header
  uint64_t term;
  uint32_t type;
  uint32_t len;
};

struct Segment {
  uint64_t first_index;
  std::string path;
  int fd = -1;        // open for append on the active (last) segment only
  uint64_t size = 0;  // current byte size
};

std::string seg_name(uint64_t first_index) {
  char buf[32];
  snprintf(buf, sizeof(buf), "%020llu.seg", (unsigned long long)first_index);
  return std::string(buf);
}

struct Wal {
  std::string dir;
  std::mutex mu;
  std::vector<Segment> segments;
  uint64_t first_index = 0;  // 0 = empty log
  uint64_t last_index = 0;
  std::vector<EntryLoc> locs;  // locs[i] = entry (first_index + i)
  uint64_t max_segment_bytes = 16ull << 20;
  std::map<std::string, std::string> kv;
  std::string err;
  bool failed = false;  // unrecoverable offset desync: refuse appends

  int open();
  int scan_segment(uint32_t seg_i);
  int append(uint64_t index, uint64_t term, uint32_t type, const uint8_t* data,
             uint32_t len);
  int get(uint64_t index, uint64_t* term, uint32_t* type, uint8_t* out,
          uint32_t cap, uint32_t* outlen);
  int truncate_suffix(uint64_t from_index);
  int compact_prefix(uint64_t to_index);
  int sync();
  int roll_segment(uint64_t next_index);
  int load_kv();
  int save_kv();
  void close_all();
};

int Wal::load_kv() {
  std::string path = dir + "/meta.kv";
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return 0;  // absent is fine
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (n < 8) { fclose(f); return 0; }
  std::vector<uint8_t> buf(n);
  if (fread(buf.data(), 1, n, f) != (size_t)n) { fclose(f); return 0; }
  fclose(f);
  uint32_t stored_crc, count;
  memcpy(&stored_crc, buf.data(), 4);
  memcpy(&count, buf.data() + 4, 4);
  if (crc32(buf.data() + 4, n - 4) != stored_crc) return 0;  // corrupt: empty
  size_t off = 8;
  for (uint32_t i = 0; i < count; i++) {
    if (off + 8 > (size_t)n) return 0;
    uint32_t kl, vl;
    memcpy(&kl, buf.data() + off, 4);
    memcpy(&vl, buf.data() + off + 4, 4);
    off += 8;
    if (off + kl + vl > (size_t)n) return 0;
    std::string k((char*)buf.data() + off, kl);
    std::string v((char*)buf.data() + off + kl, vl);
    off += kl + vl;
    kv[k] = v;
  }
  return 0;
}

int Wal::save_kv() {
  std::vector<uint8_t> buf(8, 0);
  uint32_t count = kv.size();
  memcpy(buf.data() + 4, &count, 4);
  for (auto& [k, v] : kv) {
    uint32_t kl = k.size(), vl = v.size();
    size_t off = buf.size();
    buf.resize(off + 8 + kl + vl);
    memcpy(buf.data() + off, &kl, 4);
    memcpy(buf.data() + off + 4, &vl, 4);
    memcpy(buf.data() + off + 8, k.data(), kl);
    memcpy(buf.data() + off + 8 + kl, v.data(), vl);
  }
  uint32_t crc = crc32(buf.data() + 4, buf.size() - 4);
  memcpy(buf.data(), &crc, 4);
  std::string tmp = dir + "/meta.kv.tmp", path = dir + "/meta.kv";
  int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) { err = "open meta.kv.tmp: " + std::string(strerror(errno)); return -1; }
  ssize_t w = write(fd, buf.data(), buf.size());
  if (w != (ssize_t)buf.size()) { ::close(fd); err = "short kv write"; return -1; }
  fsync(fd);
  ::close(fd);
  if (rename(tmp.c_str(), path.c_str()) != 0) {
    err = "rename meta.kv: " + std::string(strerror(errno));
    return -1;
  }
  return 0;
}

int Wal::scan_segment(uint32_t seg_i) {
  Segment& seg = segments[seg_i];
  FILE* f = fopen(seg.path.c_str(), "rb");
  if (!f) { err = "open " + seg.path; return -1; }
  uint64_t off = 0;
  std::vector<uint8_t> payload;
  for (;;) {
    RecHeader h;
    size_t r = fread(&h, 1, sizeof(h), f);
    if (r == 0) break;  // clean EOF
    if (r < sizeof(h)) break;  // torn header: truncate here
    if (h.len > (64u << 20)) break;  // implausible: treat as corruption
    payload.resize(sizeof(RecHeader) - 4 + h.len);
    memcpy(payload.data(), ((uint8_t*)&h) + 4, sizeof(RecHeader) - 4);
    if (fread(payload.data() + sizeof(RecHeader) - 4, 1, h.len, f) != h.len)
      break;  // torn payload
    if (crc32(payload.data(), payload.size()) != h.crc) break;  // corrupt tail
    // Entries must be contiguous.
    uint64_t expect = (first_index == 0) ? h.index : last_index + 1;
    if (first_index != 0 && h.index != expect) break;
    if (first_index == 0) first_index = h.index;
    last_index = h.index;
    locs.push_back(EntryLoc{seg_i, off, h.term, h.type, h.len});
    off += sizeof(RecHeader) + h.len;
  }
  fclose(f);
  seg.size = off;
  // Truncate any torn tail so appends go to a clean boundary.
  if (truncate(seg.path.c_str(), off) != 0) {
    err = "truncate " + seg.path;
    return -1;
  }
  return 0;
}

int Wal::open() {
  mkdir(dir.c_str(), 0755);
  DIR* d = opendir(dir.c_str());
  if (!d) { err = "opendir " + dir + ": " + strerror(errno); return -1; }
  std::vector<std::pair<uint64_t, std::string>> found;
  struct dirent* de;
  while ((de = readdir(d)) != nullptr) {
    std::string name = de->d_name;
    if (name.size() == 24 && name.substr(20) == ".seg")
      found.push_back({strtoull(name.c_str(), nullptr, 10), dir + "/" + name});
  }
  closedir(d);
  std::sort(found.begin(), found.end());
  for (auto& [fi, path] : found)
    segments.push_back(Segment{fi, path, -1, 0});
  for (uint32_t i = 0; i < segments.size(); i++) {
    if (scan_segment(i) != 0) return -1;
    // Corruption in a non-final segment orphans later segments: drop them.
    if (i + 1 < segments.size() &&
        (locs.empty() || segments[i + 1].first_index != last_index + 1)) {
      for (uint32_t j = i + 1; j < segments.size(); j++)
        unlink(segments[j].path.c_str());
      segments.resize(i + 1);
      break;
    }
  }
  if (!segments.empty()) {
    Segment& tail = segments.back();
    tail.fd = ::open(tail.path.c_str(), O_WRONLY | O_APPEND);
    if (tail.fd < 0) { err = "open tail: " + std::string(strerror(errno)); return -1; }
  }
  return load_kv();
}

int Wal::roll_segment(uint64_t next_index) {
  if (!segments.empty() && segments.back().fd >= 0) {
    fsync(segments.back().fd);
    ::close(segments.back().fd);
    segments.back().fd = -1;
  }
  Segment seg;
  seg.first_index = next_index;
  seg.path = dir + "/" + seg_name(next_index);
  seg.fd = ::open(seg.path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (seg.fd < 0) { err = "create segment: " + std::string(strerror(errno)); return -1; }
  seg.size = 0;
  segments.push_back(seg);
  return 0;
}

int Wal::append(uint64_t index, uint64_t term, uint32_t type,
                const uint8_t* data, uint32_t len) {
  if (failed) { err = "store poisoned by failed rollback"; return -6; }
  // Must match scan_segment's corruption heuristic: an entry the scanner
  // would reject as implausibly large must never be durably written.
  if (len > (64u << 20)) { err = "record exceeds 64MB limit"; return -5; }
  uint64_t expect = (first_index == 0) ? index : last_index + 1;
  if (index != expect) { err = "non-contiguous append"; return -2; }
  if (segments.empty() || segments.back().size >= max_segment_bytes)
    if (roll_segment(index) != 0) return -1;
  Segment& seg = segments.back();
  RecHeader h{0, len, index, term, type};
  std::vector<uint8_t> buf(sizeof(RecHeader) + len);
  memcpy(buf.data() + 4, ((uint8_t*)&h) + 4, sizeof(RecHeader) - 4);
  if (len) memcpy(buf.data() + sizeof(RecHeader), data, len);
  h.crc = crc32(buf.data() + 4, buf.size() - 4);
  memcpy(buf.data(), &h.crc, 4);
  ssize_t w = write(seg.fd, buf.data(), buf.size());
  if (w != (ssize_t)buf.size()) {
    // Roll back the partial record so a retried append lands at the
    // offset the bookkeeping will record for it (fd is O_APPEND). If the
    // rollback itself fails, offsets and file contents have diverged for
    // good — poison the store so no further append can record a wrong
    // offset for an acked entry.
    if (ftruncate(seg.fd, seg.size) != 0) failed = true;
    err = failed ? "short append; rollback failed (store poisoned)"
                 : "short append";
    return -1;
  }
  locs.push_back(EntryLoc{(uint32_t)(segments.size() - 1), seg.size, term, type, len});
  seg.size += buf.size();
  if (first_index == 0) first_index = index;
  last_index = index;
  return 0;
}

int Wal::get(uint64_t index, uint64_t* term, uint32_t* type, uint8_t* out,
             uint32_t cap, uint32_t* outlen) {
  if (first_index == 0 || index < first_index || index > last_index) return -3;
  EntryLoc& loc = locs[index - first_index];
  *term = loc.term;
  *type = loc.type;
  *outlen = loc.len;
  if (out == nullptr) return 0;  // size query
  if (cap < loc.len) return -4;
  FILE* f = fopen(segments[loc.segment].path.c_str(), "rb");
  if (!f) { err = "open segment for read"; return -1; }
  fseek(f, loc.offset + sizeof(RecHeader), SEEK_SET);
  size_t r = fread(out, 1, loc.len, f);
  fclose(f);
  if (r != loc.len) { err = "short read"; return -1; }
  return 0;
}

int Wal::truncate_suffix(uint64_t from_index) {
  if (first_index == 0 || from_index > last_index) return 0;
  if (from_index <= first_index) {
    // Everything goes.
    close_all();
    for (auto& s : segments) unlink(s.path.c_str());
    segments.clear();
    locs.clear();
    first_index = last_index = 0;
    return 0;
  }
  EntryLoc& loc = locs[from_index - first_index];
  // Drop whole segments after the one containing from_index.
  for (uint32_t j = loc.segment + 1; j < segments.size(); j++) {
    if (segments[j].fd >= 0) ::close(segments[j].fd);
    unlink(segments[j].path.c_str());
  }
  segments.resize(loc.segment + 1);
  Segment& seg = segments.back();
  if (seg.fd >= 0) { ::close(seg.fd); seg.fd = -1; }
  if (truncate(seg.path.c_str(), loc.offset) != 0) { err = "truncate suffix"; return -1; }
  seg.size = loc.offset;
  seg.fd = ::open(seg.path.c_str(), O_WRONLY | O_APPEND);
  if (seg.fd < 0) { err = "reopen after truncate"; return -1; }
  locs.resize(from_index - first_index);
  last_index = from_index - 1;
  if (locs.empty()) {
    // from_index == first_index handled above, so locs non-empty unless
    // the whole log was in later segments; normalize to empty.
    first_index = last_index = 0;
  }
  return 0;
}

int Wal::compact_prefix(uint64_t to_index) {
  // Delete whole segments whose entries are all <= to_index.
  if (first_index == 0) return 0;
  uint32_t drop = 0;
  for (uint32_t i = 0; i + 1 < segments.size(); i++) {
    if (segments[i + 1].first_index - 1 <= to_index) drop = i + 1;
    else break;
  }
  if (drop == 0) return 0;
  uint64_t new_first = segments[drop].first_index;
  for (uint32_t i = 0; i < drop; i++) unlink(segments[i].path.c_str());
  segments.erase(segments.begin(), segments.begin() + drop);
  locs.erase(locs.begin(), locs.begin() + (new_first - first_index));
  for (auto& l : locs) l.segment -= drop;
  first_index = new_first;
  return 0;
}

int Wal::sync() {
  if (!segments.empty() && segments.back().fd >= 0)
    return fsync(segments.back().fd) == 0 ? 0 : -1;
  return 0;
}

void Wal::close_all() {
  for (auto& s : segments)
    if (s.fd >= 0) { ::close(s.fd); s.fd = -1; }
}

}  // namespace

extern "C" {

void* wal_open(const char* dir, uint64_t max_segment_bytes) {
  Wal* w = new Wal();
  w->dir = dir;
  if (max_segment_bytes) w->max_segment_bytes = max_segment_bytes;
  if (w->open() != 0) {
    fprintf(stderr, "walstore: open failed: %s\n", w->err.c_str());
    delete w;
    return nullptr;
  }
  return w;
}

void wal_close(void* h) {
  Wal* w = (Wal*)h;
  w->close_all();
  delete w;
}

uint64_t wal_first_index(void* h) { return ((Wal*)h)->first_index; }
uint64_t wal_last_index(void* h) { return ((Wal*)h)->last_index; }

int wal_append(void* h, uint64_t index, uint64_t term, uint32_t type,
               const uint8_t* data, uint32_t len) {
  Wal* w = (Wal*)h;
  std::lock_guard<std::mutex> g(w->mu);
  return w->append(index, term, type, data, len);
}

int wal_get(void* h, uint64_t index, uint64_t* term, uint32_t* type,
            uint8_t* out, uint32_t cap, uint32_t* outlen) {
  Wal* w = (Wal*)h;
  std::lock_guard<std::mutex> g(w->mu);
  return w->get(index, term, type, out, cap, outlen);
}

int wal_truncate_suffix(void* h, uint64_t from_index) {
  Wal* w = (Wal*)h;
  std::lock_guard<std::mutex> g(w->mu);
  return w->truncate_suffix(from_index);
}

int wal_compact_prefix(void* h, uint64_t to_index) {
  Wal* w = (Wal*)h;
  std::lock_guard<std::mutex> g(w->mu);
  return w->compact_prefix(to_index);
}

int wal_sync(void* h) {
  Wal* w = (Wal*)h;
  std::lock_guard<std::mutex> g(w->mu);
  return w->sync();
}

int wal_kv_set(void* h, const char* key, const uint8_t* val, uint32_t len) {
  Wal* w = (Wal*)h;
  std::lock_guard<std::mutex> g(w->mu);
  w->kv[key] = std::string((const char*)val, len);
  return w->save_kv();
}

// Returns value length, or -1 if absent. Copies min(cap, len) bytes.
int wal_kv_get(void* h, const char* key, uint8_t* out, uint32_t cap) {
  Wal* w = (Wal*)h;
  std::lock_guard<std::mutex> g(w->mu);
  auto it = w->kv.find(key);
  if (it == w->kv.end()) return -1;
  uint32_t n = it->second.size();
  if (out && cap) memcpy(out, it->second.data(), std::min(cap, n));
  return (int)n;
}

const char* wal_last_error(void* h) { return ((Wal*)h)->err.c_str(); }

}  // extern "C"
