// executor — the task supervisor subprocess (C++ analog of the
// reference's re-exec'd `nomad executor`, drivers/shared/executor/:
// main.go:16-18 re-exec trick, executor.go process supervision).
//
// Why a separate native process: the supervisor OWNS the task child, so
//  - the task survives the agent dying (the agent re-attaches to the
//    EXECUTOR by pid+starttime, plugins/drivers/task_handle.go), and
//  - the exit status is durable: the supervisor records it in a status
//    file, so an agent restarted AFTER the task finished still observes
//    the real exit code (the gap called out in client/drivers.py's
//    recover(): without an owning process, exit codes read as 0).
//
// Usage:
//   executor <task_dir> <stdout> <stderr> <status_file> <mem_mb> <grace_s> -- cmd [args...]
//
// Isolation applied to the child (the portable subset of the reference's
// libcontainer executor): own session (setsid), RLIMIT_AS from the task
// memory ask, no core dumps, bounded nproc. The parent forwards SIGTERM
// to the child's process group with a 5 s grace before SIGKILL, then
// exits with the child's exit code.

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/resource.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

static pid_t g_child = -1;
static volatile sig_atomic_t g_killing = 0;
static unsigned g_grace_s = 5;  // task kill_timeout, overridden by argv

static void forward_term(int) {
  if (g_child > 0 && !g_killing) {
    // first TERM only: a stream of TERMs must not keep resetting the
    // alarm and postponing the hard kill
    g_killing = 1;
    kill(-g_child, SIGTERM);
    alarm(g_grace_s);  // configured grace period, then hard kill
  }
}

static void hard_kill(int) {
  if (g_child > 0) kill(-g_child, SIGKILL);
}

static long proc_start_time(pid_t pid) {
  // kernel start time (clock ticks since boot), /proc/<pid>/stat field 22
  // — the identity that tells a live task from a recycled pid
  char p[64];
  snprintf(p, sizeof p, "/proc/%d/stat", (int)pid);
  FILE *f = fopen(p, "r");
  if (!f) return 0;
  char buf[4096];
  size_t n = fread(buf, 1, sizeof buf - 1, f);
  fclose(f);
  buf[n] = 0;
  char *paren = strrchr(buf, ')');
  if (!paren) return 0;
  long v = 0;
  int fieldno = 2;
  for (char *tok = strtok(paren + 1, " "); tok; tok = strtok(nullptr, " ")) {
    if (++fieldno == 22) {
      v = atol(tok);
      break;
    }
  }
  return v;
}

static void write_status(const std::string &path, const std::string &line) {
  // atomic replace so a reader never sees a torn write
  std::string tmp = path + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return;
  ssize_t n = write(fd, line.c_str(), line.size());
  (void)n;
  fsync(fd);
  close(fd);
  rename(tmp.c_str(), path.c_str());
}

int main(int argc, char **argv) {
  if (argc < 9) {
    fprintf(stderr,
            "usage: executor <task_dir> <stdout> <stderr> <status> <mem_mb> "
            "<grace_s> -- cmd [args...]\n");
    return 2;
  }
  std::string task_dir = argv[1];
  std::string out_path = argv[2];
  std::string err_path = argv[3];
  std::string status_path = argv[4];
  long mem_mb = atol(argv[5]);
  long grace = atol(argv[6]);
  if (grace > 0) g_grace_s = (unsigned)grace;
  int cmd_at = -1;
  for (int i = 7; i < argc; i++) {
    if (strcmp(argv[i], "--") == 0) {
      cmd_at = i + 1;
      break;
    }
  }
  if (cmd_at < 0 || cmd_at >= argc) {
    fprintf(stderr, "executor: missing -- command\n");
    return 2;
  }

  // Block stop signals across fork so a SIGTERM delivered before the
  // handlers are registered is queued, not fatal: an unhandled TERM in
  // that window would kill the supervisor with default disposition,
  // orphaning the child session and freezing the status file at
  // "running". The parent unblocks after sigaction; the child restores
  // the mask before exec (exec preserves the signal mask).
  sigset_t stop_set, prev_set;
  sigemptyset(&stop_set);
  sigaddset(&stop_set, SIGTERM);
  sigaddset(&stop_set, SIGINT);
  sigprocmask(SIG_BLOCK, &stop_set, &prev_set);

  g_child = fork();
  if (g_child < 0) {
    perror("executor: fork");
    return 2;
  }
  if (g_child == 0) {
    // --- child: isolate, redirect, exec -------------------------------
    sigprocmask(SIG_SETMASK, &prev_set, nullptr);
    setsid();
    struct rlimit rl;
    rl.rlim_cur = rl.rlim_max = (rlim_t)(mem_mb + 512) * 1024 * 1024;
    setrlimit(RLIMIT_AS, &rl);
    rl.rlim_cur = rl.rlim_max = 0;
    setrlimit(RLIMIT_CORE, &rl);
    rl.rlim_cur = rl.rlim_max = 512;
    setrlimit(RLIMIT_NPROC, &rl);

    int ofd = open(out_path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
    int efd = open(err_path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
    if (ofd >= 0) dup2(ofd, 1);
    if (efd >= 0) dup2(efd, 2);
    if (ofd >= 0) close(ofd);
    if (efd >= 0) close(efd);
    if (chdir(task_dir.c_str()) != 0) _exit(127);
    execvp(argv[cmd_at], &argv[cmd_at]);
    dprintf(2, "executor: exec %s: %s\n", argv[cmd_at], strerror(errno));
    _exit(127);
  }

  // --- parent: supervise ----------------------------------------------
  signal(SIGTERM, forward_term);
  signal(SIGINT, forward_term);
  signal(SIGALRM, hard_kill);
  // handlers live: deliver anything queued during the blocked window
  sigprocmask(SIG_SETMASK, &prev_set, nullptr);
  write_status(status_path, "running " + std::to_string((long)g_child) +
                                " " + std::to_string(proc_start_time(g_child)) +
                                "\n");

  int wstatus = 0;
  pid_t r;
  do {
    r = waitpid(g_child, &wstatus, 0);
  } while (r < 0 && errno == EINTR);

  int code = 127;
  if (r == g_child) {
    if (WIFEXITED(wstatus)) code = WEXITSTATUS(wstatus);
    else if (WIFSIGNALED(wstatus)) code = 128 + WTERMSIG(wstatus);
  }
  write_status(status_path, "exit " + std::to_string(code) + "\n");
  return code;
}
