// executor — the task supervisor subprocess (C++ analog of the
// reference's re-exec'd `nomad executor`, drivers/shared/executor/:
// main.go:16-18 re-exec trick, executor.go process supervision).
//
// Why a separate native process: the supervisor OWNS the task child, so
//  - the task survives the agent dying (the agent re-attaches to the
//    EXECUTOR by pid+starttime, plugins/drivers/task_handle.go), and
//  - the exit status is durable: the supervisor records it in a status
//    file, so an agent restarted AFTER the task finished still observes
//    the real exit code (the gap called out in client/drivers.py's
//    recover(): without an owning process, exit codes read as 0).
//
// Usage:
//   executor <task_dir> <stdout> <stderr> <status_file> <mem_mb> <grace_s>
//            [--cgroup <name>] [--cpu-mhz <n>] -- cmd [args...]
//
// Isolation applied to the child, mirroring the reference's libcontainer
// executor (drivers/shared/executor/executor_linux.go):
//  - own session (setsid);
//  - a PER-TASK CGROUP when --cgroup is given: cgroup v2 (memory.max,
//    pids.max, cpu.max, kill via cgroup.kill) when the unified hierarchy
//    carries the controllers, else cgroup v1 (memory.limit_in_bytes,
//    pids.max, cpu.cfs_quota_us, kill by sweeping cgroup.procs). The
//    child enrolls ITSELF (writes "0" to cgroup.procs) before exec so no
//    grandchild can escape the hierarchy;
//  - rlimit fallback regardless (RLIMIT_AS from the memory ask, no core
//    dumps, bounded nproc) — on hosts without writable cgroups the task
//    still runs bounded.
// The parent forwards SIGTERM to the child's process group with a grace
// period before the hard kill (cgroup.kill / procs sweep + SIGKILL),
// removes the cgroup once empty, and exits with the child's exit code.

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/resource.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

static pid_t g_child = -1;
static volatile sig_atomic_t g_killing = 0;
static unsigned g_grace_s = 5;  // task kill_timeout, overridden by argv

// cgroup state (empty when cgroups are unavailable/not requested).
// g_cg_kill_file: v2 cgroup.kill path ("" on v1); g_cg_procs: the procs
// file to sweep for the v1 hard kill. Written before fork, read in
// signal context (only via open/write — async-signal-safe).
static char g_cg_kill_file[256] = "";
static char g_cg_procs[3][256] = {"", "", ""};

static void cg_hard_kill() {
  if (g_cg_kill_file[0]) {
    int fd = open(g_cg_kill_file, O_WRONLY);
    if (fd >= 0) {
      (void)!write(fd, "1", 1);
      close(fd);
      return;
    }
  }
  // v1: SIGKILL every pid in each controller's procs file
  for (int c = 0; c < 3; c++) {
    if (!g_cg_procs[c][0]) continue;
    int fd = open(g_cg_procs[c], O_RDONLY);
    if (fd < 0) continue;
    char buf[4096];
    ssize_t n = read(fd, buf, sizeof buf - 1);
    close(fd);
    if (n <= 0) continue;
    buf[n] = 0;
    long pid = 0;
    for (char *p = buf; *p; p++) {
      if (*p >= '0' && *p <= '9') {
        pid = pid * 10 + (*p - '0');
      } else if (pid > 0) {
        kill((pid_t)pid, SIGKILL);
        pid = 0;
      }
    }
    if (pid > 0) kill((pid_t)pid, SIGKILL);
  }
}

static void forward_term(int) {
  if (g_child > 0 && !g_killing) {
    // first TERM only: a stream of TERMs must not keep resetting the
    // alarm and postponing the hard kill
    g_killing = 1;
    kill(-g_child, SIGTERM);
    alarm(g_grace_s);  // configured grace period, then hard kill
  }
}

static void hard_kill(int) {
  if (g_child > 0) kill(-g_child, SIGKILL);
  cg_hard_kill();  // a forker that escaped the process group cannot
                   // escape the cgroup
}

static long proc_start_time(pid_t pid) {
  // kernel start time (clock ticks since boot), /proc/<pid>/stat field 22
  // — the identity that tells a live task from a recycled pid
  char p[64];
  snprintf(p, sizeof p, "/proc/%d/stat", (int)pid);
  FILE *f = fopen(p, "r");
  if (!f) return 0;
  char buf[4096];
  size_t n = fread(buf, 1, sizeof buf - 1, f);
  fclose(f);
  buf[n] = 0;
  char *paren = strrchr(buf, ')');
  if (!paren) return 0;
  long v = 0;
  int fieldno = 2;
  for (char *tok = strtok(paren + 1, " "); tok; tok = strtok(nullptr, " ")) {
    if (++fieldno == 22) {
      v = atol(tok);
      break;
    }
  }
  return v;
}

static bool write_small(const std::string &path, const std::string &val) {
  int fd = open(path.c_str(), O_WRONLY);
  if (fd < 0) return false;
  ssize_t n = write(fd, val.c_str(), val.size());
  close(fd);
  return n == (ssize_t)val.size();
}

// Create the per-task cgroup (v2 preferred, v1 split hierarchies else),
// apply limits, and fill g_cg_* for enrollment/kill. Returns the created
// dirs (newest last) for cleanup; empty = cgroups unavailable (rlimit
// fallback only). Mirrors drivers/shared/executor/executor_linux.go's
// configureCgroups.
static std::vector<std::string> cgroup_setup(const std::string &name,
                                             long mem_mb, long cpu_mhz) {
  std::vector<std::string> dirs;
  // v2 unified: needs the memory controller delegated to this level
  FILE *f = fopen("/sys/fs/cgroup/cgroup.controllers", "r");
  if (f) {
    char buf[512] = {0};
    size_t n = fread(buf, 1, sizeof buf - 1, f);
    (void)n;
    fclose(f);
    if (strstr(buf, "memory")) {
      std::string dir = "/sys/fs/cgroup/nomad-" + name;
      if (mkdir(dir.c_str(), 0755) == 0 || errno == EEXIST) {
        dirs.push_back(dir);
        if (mem_mb > 0)
          write_small(dir + "/memory.max",
                      std::to_string(mem_mb * 1024 * 1024));
        write_small(dir + "/pids.max", "512");
        if (cpu_mhz > 0)
          // 1000 MHz ask == one full core; period 100 ms
          write_small(dir + "/cpu.max",
                      std::to_string(cpu_mhz * 100) + " 100000");
        snprintf(g_cg_kill_file, sizeof g_cg_kill_file, "%s/cgroup.kill",
                 dir.c_str());
        snprintf(g_cg_procs[0], sizeof g_cg_procs[0], "%s/cgroup.procs",
                 dir.c_str());
        return dirs;
      }
    }
  }
  // v1: one dir per controller hierarchy
  struct Ctl {
    const char *ctrl;
    int slot;
  } ctls[] = {{"memory", 0}, {"pids", 1}, {"cpu", 2}};
  for (auto &c : ctls) {
    std::string dir =
        std::string("/sys/fs/cgroup/") + c.ctrl + "/nomad-" + name;
    if (mkdir(dir.c_str(), 0755) != 0 && errno != EEXIST) continue;
    bool ok = true;
    if (strcmp(c.ctrl, "memory") == 0 && mem_mb > 0)
      ok = write_small(dir + "/memory.limit_in_bytes",
                       std::to_string(mem_mb * 1024 * 1024));
    else if (strcmp(c.ctrl, "pids") == 0)
      ok = write_small(dir + "/pids.max", "512");
    else if (strcmp(c.ctrl, "cpu") == 0 && cpu_mhz > 0) {
      write_small(dir + "/cpu.cfs_period_us", "100000");
      ok = write_small(dir + "/cpu.cfs_quota_us",
                       std::to_string(cpu_mhz * 100));
    }
    if (!ok) {
      rmdir(dir.c_str());
      continue;
    }
    dirs.push_back(dir);
    snprintf(g_cg_procs[c.slot], sizeof g_cg_procs[c.slot],
             "%s/cgroup.procs", dir.c_str());
  }
  return dirs;
}

static void cgroup_cleanup(const std::vector<std::string> &dirs) {
  // procs must drain before rmdir succeeds; bounded retry
  for (int attempt = 0; attempt < 50; attempt++) {
    bool all = true;
    for (const auto &d : dirs)
      if (rmdir(d.c_str()) != 0 && errno != ENOENT) all = false;
    if (all) return;
    usleep(100 * 1000);
    cg_hard_kill();  // stragglers keep the dir busy
  }
}

static void write_status(const std::string &path, const std::string &line) {
  // atomic replace so a reader never sees a torn write
  std::string tmp = path + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return;
  ssize_t n = write(fd, line.c_str(), line.size());
  (void)n;
  fsync(fd);
  close(fd);
  rename(tmp.c_str(), path.c_str());
}

int main(int argc, char **argv) {
  if (argc < 9) {
    fprintf(stderr,
            "usage: executor <task_dir> <stdout> <stderr> <status> <mem_mb> "
            "<grace_s> -- cmd [args...]\n");
    return 2;
  }
  std::string task_dir = argv[1];
  std::string out_path = argv[2];
  std::string err_path = argv[3];
  std::string status_path = argv[4];
  long mem_mb = atol(argv[5]);
  long grace = atol(argv[6]);
  if (grace > 0) g_grace_s = (unsigned)grace;
  std::string cg_name;
  long cpu_mhz = 0;
  int cmd_at = -1;
  for (int i = 7; i < argc; i++) {
    if (strcmp(argv[i], "--") == 0) {
      cmd_at = i + 1;
      break;
    }
    if (strcmp(argv[i], "--cgroup") == 0 && i + 1 < argc)
      cg_name = argv[++i];
    else if (strcmp(argv[i], "--cpu-mhz") == 0 && i + 1 < argc)
      cpu_mhz = atol(argv[++i]);
  }
  if (cmd_at < 0 || cmd_at >= argc) {
    fprintf(stderr, "executor: missing -- command\n");
    return 2;
  }

  std::vector<std::string> cg_dirs;
  if (!cg_name.empty()) cg_dirs = cgroup_setup(cg_name, mem_mb, cpu_mhz);

  // Block stop signals across fork so a SIGTERM delivered before the
  // handlers are registered is queued, not fatal: an unhandled TERM in
  // that window would kill the supervisor with default disposition,
  // orphaning the child session and freezing the status file at
  // "running". The parent unblocks after sigaction; the child restores
  // the mask before exec (exec preserves the signal mask).
  sigset_t stop_set, prev_set;
  sigemptyset(&stop_set);
  sigaddset(&stop_set, SIGTERM);
  sigaddset(&stop_set, SIGINT);
  sigprocmask(SIG_BLOCK, &stop_set, &prev_set);

  g_child = fork();
  if (g_child < 0) {
    perror("executor: fork");
    return 2;
  }
  if (g_child == 0) {
    // --- child: isolate, redirect, exec -------------------------------
    sigprocmask(SIG_SETMASK, &prev_set, nullptr);
    setsid();
    // enroll in the task cgroup BEFORE exec: every process the task
    // forks inherits membership — escape by double-fork is impossible
    for (int c = 0; c < 3; c++) {
      if (!g_cg_procs[c][0]) continue;
      int fd = open(g_cg_procs[c], O_WRONLY);
      if (fd >= 0) {
        (void)!write(fd, "0", 1);  // "0" = the writing process itself
        close(fd);
      }
    }
    struct rlimit rl;
    // rlimits stay as the portable fallback; with a memory cgroup the
    // AS bound is left loose (cgroup RSS accounting is the real limit,
    // and a tight AS bound kills mmap-heavy runtimes spuriously)
    rl.rlim_cur = rl.rlim_max = (rlim_t)(mem_mb + 512) * 1024 * 1024;
    setrlimit(RLIMIT_AS, &rl);
    rl.rlim_cur = rl.rlim_max = 0;
    setrlimit(RLIMIT_CORE, &rl);
    rl.rlim_cur = rl.rlim_max = 512;
    setrlimit(RLIMIT_NPROC, &rl);

    int ofd = open(out_path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
    int efd = open(err_path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
    if (ofd >= 0) dup2(ofd, 1);
    if (efd >= 0) dup2(efd, 2);
    if (ofd >= 0) close(ofd);
    if (efd >= 0) close(efd);
    if (chdir(task_dir.c_str()) != 0) _exit(127);
    execvp(argv[cmd_at], &argv[cmd_at]);
    dprintf(2, "executor: exec %s: %s\n", argv[cmd_at], strerror(errno));
    _exit(127);
  }

  // --- parent: supervise ----------------------------------------------
  signal(SIGTERM, forward_term);
  signal(SIGINT, forward_term);
  signal(SIGALRM, hard_kill);
  // handlers live: deliver anything queued during the blocked window
  sigprocmask(SIG_SETMASK, &prev_set, nullptr);
  write_status(status_path, "running " + std::to_string((long)g_child) +
                                " " + std::to_string(proc_start_time(g_child)) +
                                "\n");

  int wstatus = 0;
  pid_t r;
  do {
    r = waitpid(g_child, &wstatus, 0);
  } while (r < 0 && errno == EINTR);

  int code = 127;
  if (r == g_child) {
    if (WIFEXITED(wstatus)) code = WEXITSTATUS(wstatus);
    else if (WIFSIGNALED(wstatus)) code = 128 + WTERMSIG(wstatus);
  }
  if (!cg_dirs.empty()) {
    cg_hard_kill();  // reap stray descendants the task left behind
    cgroup_cleanup(cg_dirs);
  }
  write_status(status_path, "exit " + std::to_string(code) + "\n");
  return code;
}
